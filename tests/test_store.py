from mlcomp_tpu.dag.parser import parse_dag
from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.db.store import Store

DAG = """
info: {name: s, project: t}
executors:
  a: {type: noop}
  b: {type: noop, depends: a, resources: {chips: 4}, max_retries: 1}
"""


def test_submit_and_roundtrip(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag(DAG))
    specs = store.task_specs(dag_id)
    assert [t.name for t in specs] == ["a", "b"]
    assert specs[1].resources.chips == 4
    assert specs[1].depends == ("a",)
    assert store.task_statuses(dag_id) == {
        "a": TaskStatus.NOT_RAN,
        "b": TaskStatus.NOT_RAN,
    }


def test_claim_respects_resources_and_priority(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        parse_dag(
            """
info: {name: p}
executors:
  small: {type: noop, resources: {chips: 1}}
  big: {type: noop, resources: {chips: 8, priority: 5}}
"""
        )
    )
    store.set_task_status(dag_id, ["small", "big"], TaskStatus.QUEUED)
    # only 2 chips free -> big (higher priority) does not fit, small claimed
    got = store.claim_task("w1", free_chips=2)
    assert got["name"] == "small"
    # 8 chips free -> big now claimable
    got2 = store.claim_task("w2", free_chips=8)
    assert got2["name"] == "big"
    # nothing left
    assert store.claim_task("w3", free_chips=8) is None


def test_claim_is_exclusive(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag("info: {name: x}\nexecutors:\n  a: {type: noop}"))
    store.set_task_status(dag_id, ["a"], TaskStatus.QUEUED)
    s2 = Store(tmp_db)
    first = store.claim_task("w1", free_chips=0)
    second = s2.claim_task("w2", free_chips=0)
    assert first is not None and second is None


def test_retry_budget(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag(DAG))
    store.set_task_status(dag_id, ["b"], TaskStatus.QUEUED)
    t = store.claim_task("w", free_chips=8)
    assert store.requeue_task(t["id"]) is True  # max_retries=1
    t = store.claim_task("w", free_chips=8)
    assert store.requeue_task(t["id"]) is False  # budget spent


def test_logs_and_metrics(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag(DAG))
    tid = store.task_rows(dag_id)[0]["id"]
    store.log(tid, "info", "hello")
    store.metric(tid, "loss", 1.5, step=0)
    store.metric(tid, "loss", 0.5, step=1)
    assert store.task_logs(tid)[0]["message"] == "hello"
    assert store.metric_series(tid, "loss") == [(0, 1.5), (1, 0.5)]
    assert store.metric_names(tid) == ["loss"]


def test_worker_heartbeat_and_death(tmp_db):
    import time

    store = Store(tmp_db)
    store.heartbeat("w1", chips=8)
    assert store.dead_workers(timeout_s=10.0) == []
    time.sleep(0.05)
    assert store.dead_workers(timeout_s=0.01) == ["w1"]
    store.mark_worker_dead("w1")
    assert store.dead_workers(timeout_s=0.01) == []

from mlcomp_tpu.dag.parser import parse_dag
from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.db.store import Store

DAG = """
info: {name: s, project: t}
executors:
  a: {type: noop}
  b: {type: noop, depends: a, resources: {chips: 4}, max_retries: 1}
"""


def test_submit_and_roundtrip(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag(DAG))
    specs = store.task_specs(dag_id)
    assert [t.name for t in specs] == ["a", "b"]
    assert specs[1].resources.chips == 4
    assert specs[1].depends == ("a",)
    assert store.task_statuses(dag_id) == {
        "a": TaskStatus.NOT_RAN,
        "b": TaskStatus.NOT_RAN,
    }


def test_claim_respects_resources_and_priority(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        parse_dag(
            """
info: {name: p}
executors:
  small: {type: noop, resources: {chips: 1}}
  big: {type: noop, resources: {chips: 8, priority: 5}}
"""
        )
    )
    store.set_task_status(dag_id, ["small", "big"], TaskStatus.QUEUED)
    # only 2 chips free -> big (higher priority) does not fit, small claimed
    got = store.claim_task("w1", free_chips=2)
    assert got["name"] == "small"
    # 8 chips free -> big now claimable
    got2 = store.claim_task("w2", free_chips=8)
    assert got2["name"] == "big"
    # nothing left
    assert store.claim_task("w3", free_chips=8) is None


def test_claim_is_exclusive(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag("info: {name: x}\nexecutors:\n  a: {type: noop}"))
    store.set_task_status(dag_id, ["a"], TaskStatus.QUEUED)
    s2 = Store(tmp_db)
    first = store.claim_task("w1", free_chips=0)
    second = s2.claim_task("w2", free_chips=0)
    assert first is not None and second is None


def test_retry_budget(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag(DAG))
    store.set_task_status(dag_id, ["b"], TaskStatus.QUEUED)
    t = store.claim_task("w", free_chips=8)
    assert store.requeue_task(t["id"]) is True  # max_retries=1
    t = store.claim_task("w", free_chips=8)
    assert store.requeue_task(t["id"]) is False  # budget spent


def test_logs_and_metrics(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(parse_dag(DAG))
    tid = store.task_rows(dag_id)[0]["id"]
    store.log(tid, "info", "hello")
    store.metric(tid, "loss", 1.5, step=0)
    store.metric(tid, "loss", 0.5, step=1)
    assert store.task_logs(tid)[0]["message"] == "hello"
    assert store.metric_series(tid, "loss") == [(0, 1.5), (1, 0.5)]
    assert store.metric_names(tid) == ["loss"]


def test_worker_heartbeat_and_death(tmp_db):
    import time

    store = Store(tmp_db)
    store.heartbeat("w1", chips=8)
    assert store.dead_workers(timeout_s=10.0) == []
    time.sleep(0.05)
    assert store.dead_workers(timeout_s=0.01) == ["w1"]
    store.mark_worker_dead("w1")
    assert store.dead_workers(timeout_s=0.01) == []


def test_metric_nan_stored_as_null_and_filtered(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    store.metric(tid, "loss", 1.0, step=0)
    store.metric(tid, "loss", float("nan"), step=1)
    store.metric(tid, "loss", float("inf"), step=2)
    store.metric(tid, "loss", 0.5, step=3)
    assert store.metric_series(tid, "loss") == [(0, 1.0), (3, 0.5)]
    store.close()


def test_add_report_sanitizes_nonfinite(tmp_db):
    import json as _json
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    rid = store.add_report(
        tid, "r",
        {"kind": "classification", "accuracy": float("nan"),
         "worst": [{"confidence": float("inf")}], "ok": 1.5},
    )
    raw = store._conn.execute(
        "SELECT payload FROM reports WHERE id=?", (rid,)
    ).fetchone()["payload"]
    payload = _json.loads(raw)  # spec-compliant JSON (no bare NaN)
    assert payload["accuracy"] is None
    assert payload["worst"][0]["confidence"] is None
    assert payload["ok"] == 1.5
    store.close()


def test_heartbeat_info_roundtrip_and_migration(tmp_path):
    """Host metrics ride the heartbeat; info=None keeps the last value;
    pre-info schema files gain the column via migration."""
    import json
    import sqlite3

    from mlcomp_tpu.db.store import Store

    # legacy file without the info column
    legacy = str(tmp_path / "legacy.sqlite")
    conn = sqlite3.connect(legacy)
    conn.execute(
        "CREATE TABLE workers (name TEXT PRIMARY KEY, chips INTEGER NOT"
        " NULL DEFAULT 0, busy_chips INTEGER NOT NULL DEFAULT 0,"
        " heartbeat REAL NOT NULL, status TEXT NOT NULL DEFAULT 'alive')"
    )
    conn.execute(
        "INSERT INTO workers VALUES ('old', 2, 0, 1.0, 'alive')"
    )
    conn.commit()
    conn.close()

    s = Store(legacy)
    s.heartbeat("w", chips=4, info={"load1": 0.5, "tasks": [7]})
    s.heartbeat("w", chips=4)  # liveness-only beat must not blank info
    rows = {r["name"]: r for r in s.workers()}
    assert json.loads(rows["w"]["info"]) == {"load1": 0.5, "tasks": [7]}
    assert rows["old"]["info"] is None
    s.heartbeat("w", chips=4, info={"load1": 1.5})
    assert json.loads(
        {r["name"]: r for r in s.workers()}["w"]["info"]
    )["load1"] == 1.5
    s.close()

"""Observability layer: metrics registry exposition lint, tracer ring
buffer + windowed export, multithreaded save/append safety, the serve
daemon's /metrics + /trace surfaces, and the report server's /metrics
aggregation."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from mlcomp_tpu.obs.metrics import (
    CONTENT_TYPE,
    Registry,
    default_registry,
)
from mlcomp_tpu.utils.trace import Tracer, null_tracer


# ----------------------------------------------------------- metrics unit


def test_counter_gauge_exposition_and_types():
    reg = Registry()
    c = reg.counter("x_total", "things")
    c.inc()
    c.inc(2)
    g = reg.gauge("depth", "queue depth")
    g.set(3)
    g.dec()
    text = reg.render()
    assert "# HELP x_total things" in text
    assert "# TYPE x_total counter" in text
    assert "\nx_total 3\n" in text
    assert "# TYPE depth gauge" in text
    assert "\ndepth 2" in text
    with pytest.raises(ValueError):
        c.inc(-1)  # counters cannot decrease
    with pytest.raises(ValueError):
        reg.gauge("x_total", "type clash")  # name registered as counter
    assert reg.counter("x_total", "same family") is c  # create-or-get


def test_counter_set_total_is_monotonic():
    reg = Registry()
    c = reg.counter("snap_total", "snapshot-sourced")
    c.set_total(10)
    c.set_total(7)  # racing stale snapshot: clamped, never backwards
    assert c.value() == 10
    c.set_total(12)
    assert c.value() == 12


def test_label_escaping_and_schema():
    reg = Registry()
    g = reg.gauge("lbl", "labelled", labelnames=("name",))
    g.set(1, name='we"ird\\path\nline')
    line = [
        ln for ln in reg.render().splitlines() if ln.startswith("lbl{")
    ][0]
    assert line == 'lbl{name="we\\"ird\\\\path\\nline"} 1'
    with pytest.raises(ValueError, match="expected labels"):
        g.set(1)  # missing label
    with pytest.raises(ValueError, match="expected labels"):
        g.set(1, name="x", extra="y")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad-name", "dash")


def test_histogram_cumulative_buckets_sum_count():
    reg = Registry()
    h = reg.histogram("lat_ms", "latency", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 5.0, 50.0, 5000.0):
        h.observe(v)
    text = reg.render()
    assert "# TYPE lat_ms histogram" in text
    assert 'lat_ms_bucket{le="1"} 1' in text
    assert 'lat_ms_bucket{le="10"} 3' in text
    assert 'lat_ms_bucket{le="100"} 4' in text
    assert 'lat_ms_bucket{le="+Inf"} 5' in text
    assert "lat_ms_count 5" in text
    assert "lat_ms_sum 5060.5" in text


def test_collector_runs_at_render_and_errors_are_contained():
    reg = Registry()
    calls = []

    def good():
        calls.append(1)
        reg.gauge("from_collector", "set at scrape").set(7)

    def bad():
        raise RuntimeError("broken component")

    reg.register_collector(good)
    reg.register_collector(bad)
    text = reg.render()
    assert calls == [1]
    assert "from_collector 7" in text
    text = reg.render()  # second scrape still renders
    assert "mlcomp_metrics_collector_errors_total 2" in text


def test_default_registry_is_shared():
    assert default_registry() is default_registry()


# ------------------------------------------------------------ tracer ring


def test_ring_buffer_evicts_oldest_and_counts_drops():
    tr = Tracer(max_events=3)
    for i in range(5):
        tr.instant(f"e{i}")
    evs = tr.events
    assert [e["name"] for e in evs] == ["e2", "e3", "e4"]
    assert tr.dropped == 2
    body = tr.export()
    other = body["otherData"]
    assert other["dropped_events"] == 2 and other["max_events"] == 3
    # every export carries the shared-clock stamps the fleet merger
    # (and any external consumer) aligns on
    assert other["clock_offset_us"] == (
        other["export_unix_us"] - other["export_trace_us"]
    )


def test_export_last_ms_windows_and_metadata():
    tr = Tracer(max_events=64)
    tr.instant("old", track="t1")
    time.sleep(0.08)
    tr.instant("new", track="t1")
    names = lambda body: [  # noqa: E731
        e["name"] for e in body["traceEvents"] if e["ph"] != "M"
    ]
    assert names(tr.export()) == ["old", "new"]
    assert names(tr.export(last_ms=40)) == ["new"]
    meta = [e for e in tr.export()["traceEvents"] if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "t1"
    # a complete span straddling the cutoff stays (ts + dur intersects)
    tr2 = Tracer()
    with tr2.span("long"):
        time.sleep(0.06)
    assert names(tr2.export(last_ms=30)) == ["long"]


def test_export_last_ms_keeps_begins_of_clipped_async_spans():
    """An async span that STARTED before the window but is still open
    (or ended inside it) must keep its 'b' event — Perfetto cannot
    draw a span from an unmatched end."""
    tr = Tracer()
    tr.async_begin("request", 1, cat="req")   # ends inside the window
    tr.async_begin("request", 2, cat="req")   # still open
    tr.async_begin("request", 3, cat="req")   # ended before the window
    tr.async_end("request", 3, cat="req")
    time.sleep(0.08)
    tr.async_end("request", 1, cat="req")
    body = tr.export(last_ms=40)
    evs = [(e["ph"], e["id"]) for e in body["traceEvents"]
           if e["ph"] != "M"]
    assert ("b", "1") in evs and ("e", "1") in evs  # clipped: re-admitted
    assert ("b", "2") in evs                        # open: re-admitted
    assert ("b", "3") not in evs and ("e", "3") not in evs  # fully old


def test_span_yields_args_dict_for_results():
    tr = Tracer()
    with tr.span("lookup", prompt=9) as sp:
        sp["hit_tokens"] = 4
    (ev,) = tr.events
    assert ev["args"] == {"prompt": 9, "hit_tokens": 4}


def test_async_events_correlate_by_cat_and_id():
    tr = Tracer()
    tr.async_begin("dispatch", 7, cat="disp", inflight=2)
    tr.async_instant("first_token", 7, cat="disp")
    tr.async_end("dispatch", 7, cat="disp")
    phs = [(e["ph"], e["id"], e["cat"]) for e in tr.events]
    assert phs == [("b", "7", "disp"), ("n", "7", "disp"),
                   ("e", "7", "disp")]


def test_null_tracer_async_and_export_are_silent():
    t = null_tracer()
    t.async_begin("x", 1)
    t.async_end("x", 1)
    with t.span("y", track="z") as sp:
        sp["k"] = 1
    assert t.export()["traceEvents"] == []


def test_concurrent_save_and_append_stress(tmp_path):
    """The satellite race: save() serialized the LIVE event list
    outside the lock, so a concurrent span() append during json.dump
    raised RuntimeError.  N writer threads + a save loop must coexist
    and every written file must parse."""
    tr = Tracer(str(tmp_path / "t.json"), max_events=512)
    stop = threading.Event()
    errs = []

    def writer(i):
        try:
            while not stop.is_set():
                with tr.span(f"w{i}", n=1):
                    pass
                tr.instant(f"i{i}")
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [
        threading.Thread(target=writer, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 1.5
        while time.time() < deadline:
            path = tr.save()
            json.loads(open(path).read())  # every snapshot parses
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errs, errs


# ----------------------------------------------- engine + serve surfaces


def _tiny_service(**kw):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.serve import GenerationService
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    kw.setdefault("batch_sizes", (1, 2))
    kw.setdefault("prompt_buckets", (16,))
    kw.setdefault("max_new_buckets", (8,))
    return GenerationService(model, {"params": params}, **kw)


def test_engine_latency_lifetime_samples_outlive_the_window():
    """/healthz 'samples' saturates at the reservoir's maxlen;
    'lifetime_samples' keeps counting (the long-run truth)."""
    from collections import deque

    svc = _tiny_service()
    try:
        eng = svc.engine
        eng._lat_ttft = deque(maxlen=2)  # shrink the window, host-only
        for i in range(3):
            svc.generate([1 + i, 2, 3], 2)
        lat = svc.stats()["latency"]
        assert lat["samples"] == 2           # the window saturated
        assert lat["lifetime_samples"] == 3  # the truth kept counting
    finally:
        svc.close()


def test_serve_metrics_and_trace_http_round_trip():
    from mlcomp_tpu.serve import make_http_server

    svc = _tiny_service(prefix_cache=True, prefill_chunk=8)
    httpd = make_http_server(svc, "127.0.0.1", 0, "toy")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        for i in range(2):
            svc.generate([9, 10, 11, 12, 13, 14, 15, 16, i + 1], 3)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ) as r:
            assert r.headers["Content-Type"] == CONTENT_TYPE
            text1 = r.read().decode()
        assert "# TYPE mlcomp_engine_requests_total counter" in text1
        assert "mlcomp_engine_requests_total 2" in text1
        assert "# TYPE mlcomp_engine_ttft_ms histogram" in text1
        assert "mlcomp_prefix_cache_lookups_total 2" in text1
        svc.generate([9, 10, 11, 12, 13, 14, 15, 16, 50], 3)
        text2 = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ).read().decode()
        assert "mlcomp_engine_requests_total 3" in text2

        trace = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/trace?last_ms=600000"
        ).read())
        names = {e["name"] for e in trace["traceEvents"]}
        assert {"issue", "resolve", "dispatch", "request",
                "first_token", "prefill_chunk", "insert",
                "prefix_cache.lookup"} <= names
        # dispatch lifetime spans balance begin/end
        bs = [e for e in trace["traceEvents"]
              if e["name"] == "dispatch" and e["ph"] == "b"]
        es = [e for e in trace["traceEvents"]
              if e["name"] == "dispatch" and e["ph"] == "e"]
        assert bs and len(bs) == len(es)
        # malformed last_ms -> 400, not a stack dump
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace?last_ms=-5"
            )
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def test_trace_404_for_window_batcher():
    from mlcomp_tpu.serve import make_http_server

    svc = _tiny_service(batcher="window")
    httpd = make_http_server(svc, "127.0.0.1", 0, "toy")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/trace")
        assert ei.value.code == 404
        # /metrics still works (service-level counters)
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ).read().decode()
        assert 'mlcomp_service_info{batcher="window"' in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def test_flight_recorder_and_history_can_be_disabled():
    svc = _tiny_service(
        flight_recorder_events=0, metrics_history_interval=0,
    )
    try:
        svc.generate([5, 6, 7], 2)
        assert svc.engine.recorder.events == []
        assert svc.trace()["traceEvents"] == []
        # history sampler off: the spine surfaces answer 404 (the
        # service raises, the HTTP layer maps)
        assert svc.history is None and svc.slo is None
        with pytest.raises(ValueError):
            svc.slo_status()
        with pytest.raises(ValueError):
            svc.metrics_history()
        # an SLO config without the sampler it needs is a misconfig
        with pytest.raises(ValueError):
            _tiny_service(
                metrics_history_interval=0, slo_config={},
            )
    finally:
        svc.close()


# ------------------------------------------------- report server /metrics


def test_report_server_metrics_exposition(tmp_db):
    import os
    import sys

    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.report.server import start_in_thread

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
    ))
    import obs_check

    store = Store(tmp_db)
    dag = DagSpec(name="demo", project="p", tasks=(
        TaskSpec(name="a", executor="noop"),
        TaskSpec(name="b", executor="noop", depends=("a",)),
    ))
    store.submit_dag(dag)
    store.heartbeat("worker-0", chips=8, busy_chips=2)
    srv, port = start_in_thread(tmp_db)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ) as r:
            assert r.headers["Content-Type"] == CONTENT_TYPE
            text = r.read().decode()
        samples, types = obs_check.parse_exposition(text)
        assert types["mlcomp_report_tasks"] == "gauge"
        assert samples["mlcomp_report_tasks"]['{status="not_ran"}'] == 2
        assert samples["mlcomp_report_workers_alive"][""] == 1
        assert samples["mlcomp_report_worker_chips"][
            '{worker="worker-0"}'
        ] == 8
        age = samples["mlcomp_report_worker_heartbeat_age_seconds"][
            '{worker="worker-0"}'
        ]
        assert 0 <= age < 60
        # no MLCOMP_TPU_SERVE_URL in the test env: serving series absent
        assert "mlcomp_serving_up" not in types
    finally:
        srv.shutdown()
        store.close()


def test_worker_heartbeat_registers_default_metrics(tmp_db):
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.scheduler.worker import Worker

    store = Store(tmp_db)
    try:
        w = Worker(store, name="obs-w", chips=4,
                   load_jax_executors=False)
        w._host_info()
        m = default_registry()
        assert m.counter(
            "mlcomp_worker_heartbeats_total", labelnames=("worker",)
        ).value(worker="obs-w") >= 1
        assert m.gauge(
            "mlcomp_worker_chips", labelnames=("worker",)
        ).value(worker="obs-w") == 4
    finally:
        store.close()

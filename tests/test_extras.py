"""Telegram notify sink, kaggle executors (gated), step profiler."""

import json
import os
import stat
from pathlib import Path

import numpy as np
import pytest

from mlcomp_tpu.executors import load_all
from mlcomp_tpu.executors.base import ExecutionContext, create_executor
from mlcomp_tpu.utils.notify import create_notifiers, notify_all


def test_telegram_notifier_posts_bot_api(monkeypatch):
    sent = {}

    def fake_urlopen(req, timeout=None):
        sent["url"] = req.full_url
        sent["body"] = json.loads(req.data)

        class R:
            def read(self):
                return b"{}"

        return R()

    import mlcomp_tpu.utils.notify as notify

    monkeypatch.setattr(notify.urllib.request, "urlopen", fake_urlopen)
    (n,) = create_notifiers([{"type": "telegram", "token": "T0K", "chat_id": 42}])
    notify_all([n], "dag_finished", dag_id=7, status="success")
    assert sent["url"] == "https://api.telegram.org/botT0K/sendMessage"
    assert sent["body"]["chat_id"] == "42"
    assert "dag_finished" in sent["body"]["text"]
    assert '"dag_id": 7' in sent["body"]["text"]


def test_telegram_notifier_requires_token_and_chat():
    with pytest.raises(ValueError):
        create_notifiers([{"type": "telegram", "token": "", "chat_id": "x"}])


def _ctx(tmp_path, args):
    return ExecutionContext(
        dag_id=1, task_id=1, task_name="k", args=args, workdir=str(tmp_path)
    )


def test_kaggle_executor_gated_without_cli(tmp_path, monkeypatch):
    load_all()
    monkeypatch.setenv("PATH", str(tmp_path))  # no kaggle binary anywhere
    ex = create_executor("kaggle_download", {"competition": "titanic"})
    with pytest.raises(RuntimeError, match="kaggle CLI"):
        ex.work(_ctx(tmp_path, ex.args))


def _fake_kaggle(tmp_path, log_name="kaggle.log"):
    """A stand-in 'kaggle' binary that records its argv."""
    log = tmp_path / log_name
    binary = tmp_path / "kaggle"
    binary.write_text(
        "#!/bin/sh\n"
        f'echo "$@" >> {log}\n'
        'echo "ok"\n'
    )
    binary.chmod(binary.stat().st_mode | stat.S_IEXEC)
    return binary, log


def test_kaggle_download_invokes_cli(tmp_path, monkeypatch):
    load_all()
    binary, log = _fake_kaggle(tmp_path)
    monkeypatch.setenv("KAGGLE_USERNAME", "u")
    monkeypatch.setenv("KAGGLE_KEY", "k")
    out = tmp_path / "data"
    ex = create_executor(
        "kaggle_download",
        {"competition": "titanic", "out": str(out), "kaggle_bin": str(binary)},
    )
    res = ex.work(_ctx(tmp_path, ex.args))
    assert res["path"] == str(out)
    argv = log.read_text().strip()
    assert argv.startswith("competitions download -c titanic")
    assert str(out) in argv


def test_kaggle_submit_follows_dependency_result(tmp_path, monkeypatch):
    load_all()
    binary, log = _fake_kaggle(tmp_path)
    monkeypatch.setenv("KAGGLE_USERNAME", "u")
    monkeypatch.setenv("KAGGLE_KEY", "k")
    ex = create_executor(
        "kaggle_submit",
        {
            "competition": "titanic",
            "file": str(tmp_path / "preds.csv"),
            "message": "run 1",
            "kaggle_bin": str(binary),
        },
    )
    res = ex.work(_ctx(tmp_path, ex.args))
    argv = log.read_text().strip()
    assert "competitions submit -c titanic" in argv
    assert "run 1" in argv
    assert res["output"] == "ok"


def test_kaggle_download_rejects_both_sources(tmp_path):
    load_all()
    ex = create_executor(
        "kaggle_download", {"competition": "a", "dataset": "b"}
    )
    with pytest.raises(ValueError, match="exactly one"):
        ex.work(_ctx(tmp_path, ex.args))


def test_step_profiler_writes_trace(tmp_path):
    from mlcomp_tpu.utils.profile import StepProfiler

    import jax
    import jax.numpy as jnp

    prof = StepProfiler(str(tmp_path / "prof"), start_step=1, num_steps=2)
    f = jax.jit(lambda x: x * 2 + 1)
    for step in range(5):
        prof.step(step)
        f(jnp.ones((8, 8))).block_until_ready()
    prof.close()
    produced = list((tmp_path / "prof").rglob("*"))
    assert any(p.is_file() for p in produced)  # a trace landed on disk


def test_step_profiler_close_mid_window(tmp_path):
    """close() while the window is OPEN (epoch ended mid-capture, the
    engine's /profile teardown): the trace must stop cleanly, land on
    disk, and the profiler must be permanently done — a later step()
    inside what was the window must never reopen a trace (a dangling
    jax.profiler session would break every later capture in the
    process)."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.utils.profile import StepProfiler

    prof = StepProfiler(str(tmp_path / "prof"), start_step=1, num_steps=10)
    f = jax.jit(lambda x: x * 2 + 1)
    prof.step(0)
    assert not prof.active
    prof.step(1)  # opens the window (1 <= 1 < 11)
    assert prof.active and not prof.done
    f(jnp.ones((8, 8))).block_until_ready()
    prof.close()  # mid-window: steps 2..10 never ran
    assert not prof.active and prof.done
    produced = list((tmp_path / "prof").rglob("*"))
    assert any(p.is_file() for p in produced)  # the partial trace landed
    # still inside the configured window — must NOT restart
    prof.step(2)
    assert not prof.active and prof.done
    prof.close()  # idempotent
    assert prof.done


def test_step_profiler_resume_past_window(tmp_path):
    """A restored trainer whose step counter is already past the window
    must never start a trace (the resume-safety contract in the class
    docstring — only the happy path was covered before)."""
    from mlcomp_tpu.utils.profile import StepProfiler

    prof = StepProfiler(str(tmp_path / "prof"), start_step=2, num_steps=3)
    for step in (7, 8, 9):  # resumed past stop_step = 5
        prof.step(step)
        assert not prof.active and not prof.done
    prof.flush()   # stop-only boundary on a never-started window
    assert not prof.active
    prof.close()
    # no trace directory contents were ever produced
    trace_dir = tmp_path / "prof"
    assert not trace_dir.exists() or not any(
        p.is_file() for p in trace_dir.rglob("*")
    )


def test_trainer_profile_config(tmp_path):
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "mlp", "num_classes": 4, "hidden": [8]},
        "optimizer": {"name": "sgd", "lr": 0.1},
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": 1,
        "profile": {"dir": str(tmp_path / "prof"), "start_step": 0, "num_steps": 1},
        "data": {
            "train": {
                "name": "synthetic_classification",
                "n": 64,
                "num_classes": 4,
                "dim": 8,
                "batch_size": 32,
            }
        },
    }
    tr = Trainer(cfg)
    stats = tr.fit()
    assert np.isfinite(stats["train/loss"])
    assert any(p.is_file() for p in (tmp_path / "prof").rglob("*"))

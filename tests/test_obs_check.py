"""Tier-1 wiring of tools/obs_check.py: the serve-path observability
contract (exposition lint, documented-metric presence, counter
monotonicity across scrapes, Perfetto-loadable /trace) checked against
a real toy engine + daemon, like tools/cachecheck.py wires the prefix
index's fault harness."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import obs_check  # noqa: E402


def test_obs_check_end_to_end():
    out = obs_check.run(n_requests=3)
    # both traffic phases counted, plus whatever the /profile pump sent
    assert out["requests"] >= 6
    assert out["dispatch_spans"] > 0     # flight recorder saw dispatches
    assert out["trace_events"] > 0
    assert out["profile_dispatches"] >= 1   # the capture really ran
    assert out["device_track_spans"] > 0    # and merged a device track
    assert out["device_time_ms"] > 0


def test_obs_check_cli_entrypoint():
    assert obs_check.main([]) == 0

"""cli tokenize -> token_bin -> LM training: the text-corpus data path."""

import json

import numpy as np

from mlcomp_tpu.cli import main
from mlcomp_tpu.data.datasets import create_dataset
from mlcomp_tpu.data.loader import DataLoader


def _write_corpus(tmp_path):
    (tmp_path / "a.txt").write_text("hello tpu world\n" * 40)
    (tmp_path / "b.txt").write_text("a second document of text\n" * 40)
    return tmp_path


def test_tokenize_byte_roundtrip(tmp_path, capsys):
    corpus = _write_corpus(tmp_path)
    out = tmp_path / "c.bin"
    assert main(["tokenize", str(corpus), "-o", str(out)]) == 0
    meta = json.loads((tmp_path / "c.bin.json").read_text())
    assert meta["vocab_size"] == 257 and meta["eos_id"] == 256
    stream = np.memmap(out, dtype=np.uint16, mode="r")
    assert len(stream) == meta["tokens"]
    # documents are EOS-separated; bytes decode losslessly
    text = bytes(int(t) for t in stream if t < 256).decode()
    assert "hello tpu world" in text and "second document" in text
    assert int((stream == 256).sum()) == meta["documents"]


def test_token_bin_dataset_is_memmapped(tmp_path):
    out = tmp_path / "c.bin"
    main(["tokenize", str(_write_corpus(tmp_path)), "-o", str(out)])
    d = create_dataset({"name": "token_bin", "path": str(out), "seq_len": 32})
    assert isinstance(d["x"], np.memmap)  # pages read lazily by gathers
    assert d["x"].shape[1] == 32
    assert d["_vocab_size"] == 257
    dl = DataLoader(d, batch_size=4, shuffle=True)
    batch = next(iter(dl))
    assert batch["x"].shape == (4, 32)
    assert not isinstance(batch["x"], np.memmap)  # gathered copies

    limited = create_dataset(
        {"name": "token_bin", "path": str(out), "seq_len": 32, "limit": 2}
    )
    assert limited["x"].shape[0] == 2


def test_token_bin_trains_lm(tmp_path):
    out = tmp_path / "c.bin"
    main(["tokenize", str(_write_corpus(tmp_path)), "-o", str(out)])
    from mlcomp_tpu.scheduler.local import run_dag_local

    dag = {
        "info": {"name": "textlm", "project": "t"},
        "executors": {
            "train": {
                "type": "train",
                "stage": "train",
                "args": {
                    "model": {
                        "name": "transformer_lm", "vocab_size": 257,
                        "hidden": 32, "layers": 1, "heads": 2,
                    },
                    "optimizer": {"name": "adam", "lr": 1e-3},
                    "loss": "lm_cross_entropy",
                    "metrics": [],
                    "epochs": 1,
                    "data": {
                        "train": {
                            "name": "token_bin", "path": str(out),
                            "seq_len": 32, "batch_size": 8,
                        }
                    },
                    "project": "t", "dag_name": "textlm",
                    "storage_root": str(tmp_path / "storage"),
                },
            }
        },
    }
    results = run_dag_local(
        dag, workers=1, db_path=str(tmp_path / "db.sqlite"),
        workdir=str(tmp_path),
    )
    assert {s.value for s in results.values()} == {"success"}

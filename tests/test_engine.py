"""Continuous-batching engine: greedy equality with bare generate,
mid-decode join (the round-3 window batcher made late arrivals wait for
the whole running batch), token streaming, and knob parity."""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import DecodeEngine
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import GenerationService
from mlcomp_tpu.train.state import init_model


# shared compiled-program pools per engine config (the _fns idiom
# from tests/test_engine_fused_admit.py, in-place variant): engines
# with identical geometry compile their dispatch/prefill/insert
# families once for the whole module — pipeline depth and host knobs
# never change the programs
_FNS: dict = {}


def _pooled(eng, *key):
    eng._fns = _FNS.setdefault(key, eng._fns)
    return eng


def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


def _reference(model, params, ids, n_new, bucket=16, **kw):
    """Bare generate on the same left-padded bucket the engine uses."""
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask), **kw,
    )
    return np.asarray(out)[0, bucket:].tolist()


@pytest.mark.parametrize("kv_quant", [False, True])
def test_engine_greedy_matches_generate(kv_quant):
    model, params = _model_and_params(kv_quant)
    eng = DecodeEngine(model, {"params": params}, slots=4,
                       prompt_buckets=(16,), max_new_cap=8)
    try:
        rs = np.random.RandomState(1)
        prompts = [rs.randint(1, 64, n).tolist() for n in (5, 9, 13)]
        futs = [eng.submit(p, 6) for p in prompts]
        for p, f in zip(prompts, futs):
            got = f.result(timeout=300)
            assert got["ids"] == _reference(model, params, p, 6), p
    finally:
        eng.close()


def test_engine_mid_decode_join_and_no_starvation():
    """A request arriving mid-decode starts within a couple of steps —
    it does NOT wait for the running generation to drain — and a short
    request finishes before a long one that started earlier (impossible
    under the window batcher, whose batches run to completion).
    K=1 keeps the round-4 per-token join bound; the K>1 bound has its
    own test below.  pipeline_depth=1 + staged admission pin the
    SYNCHRONOUS loop whose tight bound this asserts (the fused default
    trades one extra decode step of join latency for a never-pausing
    decode stream — its bound lives in test_engine_fused_admit.py);
    the depth-2 bound (one extra in-flight dispatch) lives in
    test_engine_pipeline.py."""
    model, params = _model_and_params()
    eng = _pooled(DecodeEngine(model, {"params": params}, slots=2,
                                prompt_buckets=(16,), max_new_cap=16,
                                steps_per_dispatch=1, pipeline_depth=1,
                                fused_admission=False),
                  "s2b16c16k1")
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit([3, 14, 15, 9, 2], 12, stream=qa)
        first_a = qa.get(timeout=300)   # A is decoding now
        qb: "queue.Queue" = queue.Queue()
        step_at_submit = eng.step_count
        fb = eng.submit([7, 3, 44], 2, stream=qb)
        first_b = qb.get(timeout=300)
        ra, rb = fa.result(timeout=300), fb.result(timeout=300)
        assert first_a["step"] == 1
        # B's first token lands within two step boundaries of its
        # submission (one for the in-flight step, one for its own)
        assert first_b["step"] <= step_at_submit + 2, (
            first_b, step_at_submit
        )
        # B (2 tokens) finished while A (12) was still going
        last_b = first_b["step"] + 1
        assert last_b < 12, last_b
        # and neither output is perturbed by sharing the engine
        assert ra["ids"] == _reference(model, params, [3, 14, 15, 9, 2], 12)
        assert rb["ids"] == _reference(model, params, [7, 3, 44], 2)
        assert len(rb["ids"]) == 2 and len(ra["ids"]) == 12
    finally:
        eng.close()


def test_engine_streaming_order_and_final_result():
    model, params = _model_and_params()
    eng = _pooled(DecodeEngine(model, {"params": params}, slots=2,
                                prompt_buckets=(16,), max_new_cap=8),
                  "s2b16c8")
    try:
        q: "queue.Queue" = queue.Queue()
        fut = eng.submit([5, 6, 7], 5, logprobs=True, stream=q)
        streamed = []
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            streamed.append(item)
        final = fut.result(timeout=60)
        assert [s["token"] for s in streamed] == final["ids"]
        assert [s["logprob"] for s in streamed] == final["logprobs"]
        assert [s["step"] for s in streamed] == sorted(
            s["step"] for s in streamed
        )
    finally:
        eng.close()


def test_engine_eos_and_repetition_penalty_match_generate():
    model, params = _model_and_params()
    eng = _pooled(DecodeEngine(model, {"params": params}, slots=2,
                                prompt_buckets=(16,), max_new_cap=8),
                  "s2b16c8")
    try:
        ids = [3, 14, 15, 9, 2]
        # greedy with repetition penalty == generate's rowwise-rp path
        got = eng.submit(ids, 6, repetition_penalty=1.5).result(timeout=300)
        want = _reference(
            model, params, ids, 6,
            temperature=jnp.zeros((1,)),
            repetition_penalty=jnp.asarray([1.5]),
        )
        assert got["ids"] == want
        # eos: find greedy's first token, then declare it the EOS
        probe = eng.submit(ids, 4).result(timeout=300)
        first = probe["ids"][0]
        stopped = eng.submit(ids, 4, eos_id=first).result(timeout=300)
        assert stopped["ids"] == [first]
    finally:
        eng.close()


def test_service_defaults_to_continuous_and_streams_http():
    """GenerationService wires the engine in by default (no mesh) and
    the HTTP endpoint streams SSE tokens that reassemble to the
    non-streamed result."""
    import json
    import socket
    import threading
    import urllib.request

    from mlcomp_tpu.serve import serve_http

    model, params = _model_and_params()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(8, 16), max_new_buckets=(4, 8),
    )
    assert svc.batcher == "continuous" and svc.engine is not None
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    t = threading.Thread(
        target=serve_http, args=(svc,), kwargs={"port": port}, daemon=True,
    )
    t.start()
    import time as _t

    body = json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 4}).encode()
    for _ in range(50):
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                plain = json.loads(r.read())
            break
        except OSError:
            _t.sleep(0.1)
    else:
        raise AssertionError("server never came up")
    assert len(plain["ids"]) == 4

    sbody = json.dumps({
        "prompt": [5, 6, 7], "max_new_tokens": 4, "stream": True,
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=sbody,
        headers={"Content-Type": "application/json"},
    )
    events = []
    with urllib.request.urlopen(req) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for line in r:
            line = line.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[6:]))
    toks = [e["token"] for e in events if "token" in e]
    final = [e for e in events if e.get("done")]
    assert len(final) == 1 and final[0]["ids"] == plain["ids"]
    assert toks == plain["ids"]
    svc.close()


def test_engine_validation_and_service_window_stream_refusal():
    model, params = _model_and_params()
    eng = _pooled(DecodeEngine(model, {"params": params}, slots=2,
                                prompt_buckets=(16,), max_new_cap=8),
                  "s2b16c8")
    try:
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match="cap"):
            eng.submit([1], 99)
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit([1] * 20, 4)
    finally:
        eng.close()
    svc = GenerationService(
        model, {"params": params}, batcher="window", batch_sizes=(1,),
        prompt_buckets=(16,), max_new_buckets=(8,),
    )
    try:
        with pytest.raises(ValueError, match="streaming"):
            svc.submit([1, 2], 4, stream=queue.Queue())
    finally:
        svc.close()


def test_engine_quant_kernel_matches_generate():
    """The engine's weight prep mirrors generate's (nonkernel dequant +
    fold): int8 kernel serving through the continuous batcher produces
    generate's exact greedy tokens."""
    from mlcomp_tpu.ops.quant import quantize_params

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 1, "heads": 2, "mlp_dim": 512, "dtype": "float32",
        "kv_quant": True,
    })
    ids = [3, 14, 15, 9, 2]
    prompt = jnp.asarray(np.random.RandomState(7).randint(1, 128, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    qparams = quantize_params(params, min_size=1024)
    eng = DecodeEngine(model, {"params": qparams}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8,
                       quant_kernel=True)
    try:
        got = eng.submit(ids, 5).result(timeout=300)
    finally:
        eng.close()
    bucket = np.full((1, 16), 0, np.int32)
    mask = np.zeros((1, 16), bool)
    bucket[0, 16 - len(ids):] = ids
    mask[0, 16 - len(ids):] = True
    want = generate(
        model, {"params": qparams}, jnp.asarray(bucket), 5,
        prompt_mask=jnp.asarray(mask), quant_kernel=True,
    )
    assert got["ids"] == np.asarray(want)[0, 16:].tolist()


def test_engine_slot_churn_keeps_outputs_exact():
    """8 mixed-budget requests through 2 slots: every slot gets reused
    several times, with different prompts, budgets, eos and penalty
    knobs — stale state from a previous occupant (cache rows, presence
    mask, last logits) must never leak into the next one."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=12)
    try:
        rs = np.random.RandomState(9)
        reqs = []
        for i in range(8):
            ids = rs.randint(1, 64, rs.randint(3, 14)).tolist()
            n_new = int(rs.randint(2, 12))
            rp = 1.5 if i % 3 == 0 else 1.0
            reqs.append((ids, n_new, rp, eng.submit(
                ids, n_new, repetition_penalty=rp,
            )))
        for ids, n_new, rp, fut in reqs:
            got = fut.result(timeout=600)
            kw = {}
            if rp != 1.0:
                kw = {"temperature": jnp.zeros((1,)),
                      "repetition_penalty": jnp.asarray([rp])}
            want = _reference(model, params, ids, n_new, **kw)
            assert got["ids"] == want, (ids, n_new, rp, got["ids"], want)
        assert eng.stats()["prefills"] == 8
    finally:
        eng.close()


def test_engine_k_step_dispatch_matches_and_bounds_join():
    """K>1 amortizes host dispatch: greedy outputs stay EXACTLY equal to
    bare generate (the inner lax.scan replicates the per-token math),
    eos still stops a row mid-dispatch, and a mid-decode join lands
    within ~2K steps of submission (one in-flight dispatch + admission
    + its own first dispatch).  pipeline_depth=1: the ~2K bound is the
    synchronous loop's; pipelined joins add K per extra in-flight
    dispatch (test_engine_pipeline.py)."""
    K = 4
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=16,
                       steps_per_dispatch=K, pipeline_depth=1)
    try:
        ids = [3, 14, 15, 9, 2]
        got = eng.submit(ids, 11).result(timeout=300)  # not a K multiple
        assert got["ids"] == _reference(model, params, ids, 11)
        st = eng.stats()
        assert st["dispatches"] >= 1
        assert st["steps"] == st["dispatches"] * K
        # eos mid-dispatch: row stops emitting on device
        first = got["ids"][0]
        stopped = eng.submit(ids, 11, eos_id=first).result(timeout=300)
        assert stopped["ids"] == [first]
        # join bound: ~2K steps (in-flight dispatch + admission + own)
        qa: "queue.Queue" = queue.Queue()
        eng.submit([5, 6, 7], 16, stream=qa)
        qa.get(timeout=300)  # A is decoding
        step_at_submit = eng.step_count
        qb: "queue.Queue" = queue.Queue()
        eng.submit([7, 3, 44], 2, stream=qb)
        first_b = qb.get(timeout=300)
        assert first_b["step"] <= step_at_submit + 2 * K + 1, (
            first_b, step_at_submit
        )
    finally:
        eng.close()


def test_engine_chunked_admission_keeps_active_rows_advancing():
    """r4 verdict missing #4: a max-bucket admission must not stall the
    active rows for its whole prefill — chunks interleave with decode
    dispatches, so the active row emits tokens BETWEEN the joiner's
    chunks (strictly before the joiner's first token), and all-pad
    chunks of a short prompt are skipped outright."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16, 64), max_new_cap=24,
                       steps_per_dispatch=1, prefill_chunk=16)
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit([3, 14, 15, 9, 2], 20, stream=qa)
        qa.get(timeout=300)  # A decoding
        # B fills the 64 bucket: 60 real tokens -> chunk 0 (all real
        # from slot 4 on) .. chunk 3, i.e. 4 chunks of 16
        ids_b = np.random.RandomState(3).randint(1, 64, 60).tolist()
        qb: "queue.Queue" = queue.Queue()
        fb = eng.submit(ids_b, 2, stream=qb)
        first_b = qb.get(timeout=300)
        # count A tokens that landed strictly before B's first token:
        # with 4 chunks interleaved, A advanced >= 3 times in between
        a_before = 0
        while True:
            item = qa.get(timeout=300)
            if item is None or item["step"] >= first_b["step"]:
                break
            a_before += 1
        assert a_before >= 3, a_before
        ra, rb = fa.result(timeout=300), fb.result(timeout=300)
        assert ra["ids"] == _reference(model, params, [3, 14, 15, 9, 2],
                                       20, bucket=16)
        assert rb["ids"] == _reference(model, params, ids_b, 2, bucket=64)
        assert eng.stats()["prefill_chunks"] >= 4 + 1  # B's 4 + A's 1
    finally:
        eng.close()


def test_engine_pad_chunk_skip_is_exact():
    """A short prompt in a big bucket: the admission skips its all-pad
    leading chunks (cache_index pre-advanced), and the output still
    exactly matches bare generate on the same bucket."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(64,), max_new_cap=8,
                       prefill_chunk=16)
    try:
        ids = [7, 3, 44]  # 3 real tokens: chunks 0-2 are all-pad
        got = eng.submit(ids, 6).result(timeout=300)
        assert got["ids"] == _reference(model, params, ids, 6, bucket=64)
        assert eng.stats()["prefill_chunks"] == 1  # 3 of 4 skipped
    finally:
        eng.close()


def test_engine_close_under_load_and_wedged_abandon():
    """r4 verdict weak #4: close() mutates shared state only after the
    step thread provably exited.  Normal path: close mid-decode under
    load resolves EVERY future (result or 'closed' error) and join
    completes.  Wedged path: a dispatch that never returns within the
    timeout flips the engine to abandoned — queued futures fail, new
    submits fail fast, and slot state is left for the (possibly still
    running) thread."""
    import time as _t

    model, params = _model_and_params()
    eng = _pooled(DecodeEngine(model, {"params": params}, slots=2,
                                prompt_buckets=(16,), max_new_cap=16,
                                steps_per_dispatch=1),
                  "s2b16c16k1")
    futs = [eng.submit([3, 14, 15, 9, 2], 16) for _ in range(4)]
    eng.close()  # mid-decode: 2 active rows + 2 queued
    assert not eng._thread.is_alive()
    for f in futs:
        assert f.done()
        try:
            f.result(timeout=0)
        except RuntimeError as e:
            assert "closed" in str(e)
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit([1], 2)

    # wedged dispatch: swap the compiled dispatch fn for a sleeper
    eng2 = _pooled(DecodeEngine(model, {"params": params}, slots=2,
                                 prompt_buckets=(16,), max_new_cap=16,
                                 steps_per_dispatch=1),
                   "s2b16c16k1")
    eng2.submit([3, 14, 15, 9, 2], 4).result(timeout=300)  # warm
    real = eng2._dispatch_fn()
    release = threading.Event()

    def wedged(*a, **kw):
        release.wait(timeout=30)
        return real(*a, **kw)

    eng2._fns[("dispatch", eng2.steps_per_dispatch)] = wedged
    f_active = eng2.submit([3, 14, 15, 9, 2], 4)
    _t.sleep(0.3)  # let the thread enter the wedged dispatch
    f_queued = eng2.submit([1, 2], 2)
    eng2.close(timeout=0.5)
    assert eng2._abandoned
    assert f_queued.done()  # queued work failed by the drain
    with pytest.raises(RuntimeError, match="down|closed"):
        eng2.submit([1], 2)
    # the active row's future is NOT resolved by close (the thread may
    # still own it); releasing the wedge lets the thread run on, and
    # nothing crashes
    assert not f_active.done() or f_active.exception() is None
    release.set()
    eng2._thread.join(timeout=60)
    assert not eng2._thread.is_alive()

"""Serving resilience semantics: per-request deadlines and
cancellation (queued, in-flight, mid-prefill), admission-control
backpressure (429 + Retry-After), pipeline-depth equality for
survivors when a neighbor is cancelled, and prefix-cache fault
containment (degraded bypass returns exact tokens).  The end-to-end
fault/recovery story (watchdog restarts, 503 health) lives in
tools/chaoscheck.py, wired tier-1 by test_chaoscheck.py."""

import json
import queue
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import (
    DeadlineExceeded,
    DecodeEngine,
    RequestCancelled,
)
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import BackpressureError, GenerationService
from mlcomp_tpu.train.state import init_model
from mlcomp_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


def _model_and_params(seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


def _reference(model, params, ids, n_new, bucket=16):
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask),
    )
    return np.asarray(out)[0, bucket:].tolist()


def test_deadline_expiry_mid_decode_frees_slot_and_pins():
    """A request whose deadline lands mid-decode fails with
    DeadlineExceeded at a dispatch boundary, its slot frees for the
    next admission, and any prefix-cache pins are released."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=32,
                       steps_per_dispatch=1)
    try:
        base = eng.submit([3, 14, 15], 6).result(timeout=300)["ids"]  # warm
        # slow every resolve so a 32-token budget cannot finish within
        # the deadline — expiry is guaranteed mid-decode, not flaky
        faults.arm("engine.resolve", flavor="sleep", times=-1,
                   seconds=0.02)
        q: "queue.Queue" = queue.Queue()
        fut = eng.submit([3, 14, 15], 32, deadline_s=0.15, stream=q)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=60)
        assert fut.exception().status == "deadline_exceeded"
        # the stream was terminated too
        items = []
        while True:
            item = q.get(timeout=10)
            if item is None:
                break
            items.append(item)
        assert len(items) < 32  # it really died mid-decode
        faults.disarm_all()
        st = eng.stats()
        assert st["deadline_exceeded"] == 1
        assert st["active_slots"] == 0  # the slot is free again
        # and the engine still produces exact tokens afterwards
        assert eng.submit([3, 14, 15], 6).result(timeout=300)["ids"] == base
    finally:
        eng.close()


def test_deadline_frees_prefix_cache_state():
    """Deadline retirement with a prefix cache: no outstanding leases
    or pinned nodes survive the retirement."""
    model, params = _model_and_params()
    from mlcomp_tpu.cache import PrefixKVCache

    pc = PrefixKVCache(max_bytes=1 << 28)
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=32,
                       prefill_chunk=8, prefix_cache=pc,
                       steps_per_dispatch=1)
    try:
        shared = [9, 10, 11, 12, 13, 14, 15, 16, 17]
        eng.submit(shared + [1], 4).result(timeout=300)
        pc.flush()
        faults.arm("engine.resolve", flavor="sleep", times=-1,
                   seconds=0.02)
        # this request LEASES the cached prefix on admission, then dies
        fut = eng.submit(shared + [2], 32, deadline_s=0.15)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=60)
        faults.disarm_all()
        pc.flush()
        cs = pc.stats()
        assert cs["outstanding_leases"] == 0, cs
        assert cs["pinned_nodes"] == 0, cs
        pc.index.check_invariants()
    finally:
        eng.close()


def test_cancel_queued_vs_inflight():
    """Cancelling a QUEUED request fails it without it ever taking a
    slot; cancelling an IN-FLIGHT request retires the row at the next
    boundary and frees its slot for the queued successor."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=1,
                       prompt_buckets=(16,), max_new_cap=32,
                       steps_per_dispatch=1)
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit([5, 6, 7], 32, stream=qa)
        qa.get(timeout=300)  # A holds the one slot, decoding
        fb = eng.submit([5, 6, 8], 4)   # queued behind A
        prefills0 = eng.stats()["prefills"]
        assert eng.cancel(fb.rid)
        with pytest.raises(RequestCancelled):
            fb.result(timeout=60)
        # B never prefilled — cancelled straight out of the queue
        assert eng.stats()["prefills"] == prefills0
        assert eng.cancel(fa.rid)
        with pytest.raises(RequestCancelled):
            fa.result(timeout=60)
        # slot freed: a fresh request decodes exactly
        got = eng.submit([5, 6, 8], 4).result(timeout=300)
        assert got["ids"] == _reference(model, params, [5, 6, 8], 4)
        st = eng.stats()
        assert st["cancelled"] == 2 and st["active_slots"] == 0
        # unknown rids are reported dead, not queued for a ghost sweep
        assert not eng.cancel(99999)
    finally:
        eng.close()


def test_backpressure_429_with_retry_after():
    """Queue overflow fast-fails with BackpressureError at the service
    and 429 + Retry-After over HTTP; draining the queue re-admits."""
    from mlcomp_tpu.serve import make_http_server

    model, params = _model_and_params()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1,),
        prompt_buckets=(16,), max_new_buckets=(8, 32),
        max_queue_depth=2,
    )
    httpd = make_http_server(svc, "127.0.0.1", 0, "bp-test")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        svc.submit([5, 6, 7], 4).result(timeout=300)  # warm/compile
        # occupy the ONE slot with a long request, then wedge every
        # dispatch: later submissions stay queued (no free slot), so
        # the overflow state holds still while the contract is probed
        qa: "queue.Queue" = queue.Queue()
        fa = svc.submit([5, 6, 7], 32, stream=qa)
        qa.get(timeout=300)  # decoding now
        faults.arm("engine.dispatch", flavor="sleep", times=-1,
                   seconds=0.5)
        futs = []
        rejected = None
        for _ in range(16):
            try:
                futs.append(svc.submit([5, 6, 7], 8))
            except BackpressureError as e:
                rejected = e
                break
        assert len(futs) == 2, len(futs)  # exactly the queue bound
        assert rejected is not None, "queue bound never enforced"
        assert rejected.reason == "queue_full"
        assert 1.0 <= rejected.retry_after_s <= 60.0
        # the HTTP surface: 429, Retry-After header, machine-readable body
        body = json.dumps({"prompt": [5, 6, 7],
                           "max_new_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=60)
        assert exc.value.code == 429
        retry_after = int(exc.value.headers["Retry-After"])
        assert 1 <= retry_after <= 60
        payload = json.loads(exc.value.read())
        assert payload["reason"] == "queue_full"
        assert svc.stats()["rejected"]["queue_full"] >= 2
        faults.disarm_all()
        fa.result(timeout=300)
        for f in futs:
            f.result(timeout=300)  # queued work still completes
        # drained: admission is open again
        svc.submit([5, 6, 7], 4).result(timeout=300)
    finally:
        faults.disarm_all()
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def test_pipeline_depth_equality_with_cancelled_neighbor():
    """Cancelling one request must not perturb its neighbors' tokens at
    ANY pipeline depth: survivors are bit-identical between depth 1 and
    depth 2, and equal to bare generate."""
    model, params = _model_and_params()
    survivors = {}
    for depth in (1, 2):
        eng = DecodeEngine(model, {"params": params}, slots=2,
                           prompt_buckets=(16,), max_new_cap=24,
                           steps_per_dispatch=1, pipeline_depth=depth)
        try:
            qa: "queue.Queue" = queue.Queue()
            fa = eng.submit([3, 14, 15, 9, 2], 20, stream=qa)
            qb: "queue.Queue" = queue.Queue()
            fb = eng.submit([7, 3, 44], 24, stream=qb)
            qa.get(timeout=300)
            qb.get(timeout=300)  # both decoding
            assert eng.cancel(fb.rid)
            with pytest.raises(RequestCancelled):
                fb.result(timeout=60)
            survivors[depth] = fa.result(timeout=300)["ids"]
        finally:
            eng.close()
    assert survivors[1] == survivors[2]
    assert survivors[1] == _reference(
        model, params, [3, 14, 15, 9, 2], 20
    )


def test_cache_fault_degraded_bypass_returns_exact_tokens():
    """An armed cache.lookup raise is contained to a cache-bypass: the
    request succeeds with the exact cold-prefill tokens, reports 0
    cache_hit_tokens, and increments the degraded counter."""
    model, params = _model_and_params()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
        prefix_cache=True, prefill_chunk=8,
    )
    try:
        shared = [9, 10, 11, 12, 13, 14, 15, 16, 17]
        base = svc.submit(shared + [1], 4).result(timeout=300)
        svc.prefix_cache.flush()
        # sanity: the prefix actually hits when nothing is armed
        hit = svc.submit(shared + [1], 4).result(timeout=300)
        assert hit["cache_hit_tokens"] > 0
        assert hit["ids"] == base["ids"]
        faults.arm("cache.lookup", flavor="raise", times=1)
        deg = svc.submit(shared + [1], 4).result(timeout=300)
        assert deg["ids"] == base["ids"]
        assert deg["cache_hit_tokens"] == 0
        st = svc.engine.stats()
        assert st["cache_degraded"] == 1
        # containment, not poisoning: the next request hits again
        again = svc.submit(shared + [1], 4).result(timeout=300)
        assert again["cache_hit_tokens"] > 0
        assert again["ids"] == base["ids"]
    finally:
        svc.close()


def test_deadline_validation_and_window_batcher_refusal():
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=1,
                       prompt_buckets=(16,), max_new_cap=8)
    try:
        with pytest.raises(ValueError, match="deadline_s"):
            eng.submit([1, 2], 4, deadline_s=0)
    finally:
        eng.close()
    svc = GenerationService(
        model, {"params": params}, batcher="window", batch_sizes=(1,),
        prompt_buckets=(16,), max_new_buckets=(8,),
    )
    try:
        with pytest.raises(ValueError, match="deadline"):
            svc.submit([1, 2], 4, deadline_s=5.0)
        assert not svc.cancel(1)  # no cancellation path either
    finally:
        svc.close()

"""Failure handling: static race detection + fault-injected crash recovery."""

import os
import subprocess
import sys
import time

import pytest

from mlcomp_tpu.dag.graph import DagValidationError, detect_write_races, validate_dag
from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.supervisor import Supervisor
from mlcomp_tpu.scheduler.worker import Worker
from mlcomp_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


# ---------------------------------------------------------------- races


def test_race_detector_flags_concurrent_writers():
    tasks = [
        TaskSpec(name="a", executor="noop", args={"out": "preds.npz"}),
        TaskSpec(name="b", executor="noop", args={"out": "./preds.npz"}),
    ]
    races = detect_write_races(tasks)
    assert len(races) == 1 and "'a'" in races[0] and "'b'" in races[0]
    with pytest.raises(DagValidationError, match="race"):
        validate_dag(DagSpec(name="d", project="p", tasks=tuple(tasks)))


def test_race_detector_allows_ordered_writers():
    tasks = [
        TaskSpec(name="a", executor="noop", args={"out": "x.npz"}),
        TaskSpec(name="mid", executor="noop", depends=("a",)),
        TaskSpec(name="b", executor="noop", depends=("mid",), args={"out": "x.npz"}),
    ]
    assert detect_write_races(tasks) == []
    validate_dag(DagSpec(name="d", project="p", tasks=tuple(tasks)))


def test_race_detector_distinct_paths_ok():
    tasks = [
        TaskSpec(name="a", executor="noop", args={"out": "a.npz"}),
        TaskSpec(name="b", executor="noop", args={"ckpt_dir": "ck/b"}),
    ]
    assert detect_write_races(tasks) == []


# ------------------------------------------------------------ fault arming


def test_inject_noop_when_unarmed():
    faults.inject("worker.after_claim")  # must not raise


def test_arm_raise_fires_limited_times():
    faults.arm("p", times=2)
    with pytest.raises(faults.FaultInjected):
        faults.inject("p")
    with pytest.raises(faults.FaultInjected):
        faults.inject("p")
    faults.inject("p")  # budget spent


# --------------------------------------------------- crash recovery (raise)


def _submit_noop(store, max_retries=1):
    dag_id = store.submit_dag(
        DagSpec(
            name="d",
            project="p",
            tasks=(TaskSpec(name="t", executor="noop", max_retries=max_retries),),
        )
    )
    return dag_id, store.task_rows(dag_id)[0]["id"]


def test_worker_crash_after_claim_recovers_via_reap(tmp_db):
    """A worker that dies after claiming leaves the task in_progress; the
    supervisor's failure detector requeues it and a healthy worker finishes."""
    store = Store(tmp_db)
    dag_id, tid = _submit_noop(store)
    sup = Supervisor(store, worker_timeout_s=0.05)
    sup.tick()  # queue the task
    assert store.task_statuses(dag_id)["t"] == TaskStatus.QUEUED

    faults.arm("worker.after_claim", flavor="raise")
    w = Worker(store, name="doomed", chips=0, load_jax_executors=False)
    from mlcomp_tpu.executors import load_all

    load_all()
    with pytest.raises(faults.FaultInjected):
        w.run_once()
    # task stranded in_progress on the dead worker
    assert store.task_statuses(dag_id)["t"] == TaskStatus.IN_PROGRESS

    time.sleep(0.1)  # let the heartbeat go stale
    sup.tick()  # failure detector: reap + requeue (retry budget 1)
    assert store.task_statuses(dag_id)["t"] == TaskStatus.QUEUED

    w2 = Worker(store, name="healthy", chips=0, load_jax_executors=False)
    assert w2.run_once()
    assert store.task_statuses(dag_id)["t"] == TaskStatus.SUCCESS
    assert sup.tick()[dag_id] == "success"
    store.close()


def test_worker_crash_retries_exhausted_fails_task(tmp_db):
    store = Store(tmp_db)
    dag_id, tid = _submit_noop(store, max_retries=0)
    sup = Supervisor(store, worker_timeout_s=0.05)
    sup.tick()
    faults.arm("worker.after_claim", flavor="raise")
    w = Worker(store, name="doomed", chips=0, load_jax_executors=False)
    from mlcomp_tpu.executors import load_all

    load_all()
    with pytest.raises(faults.FaultInjected):
        w.run_once()
    time.sleep(0.1)
    sup.tick()
    assert store.task_statuses(dag_id)["t"] == TaskStatus.FAILED
    assert sup.tick()[dag_id] == "failed"
    store.close()


# ---------------------------------------------------- crash recovery (kill)


_KILL_WORKER = """
import sys
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.worker import Worker
from mlcomp_tpu.executors import load_all
load_all()
store = Store(sys.argv[1])
w = Worker(store, name="killed", chips=0, load_jax_executors=False)
w.run_once()
print("survived")  # must be unreachable with the kill fault armed
"""


def test_hard_kill_mid_task_recovers(tmp_db):
    """os._exit(137) between executor completion and finish_task: the task
    result is lost, the supervisor reaps the silent worker, and a retry
    lands the result — the preemption/OOM-kill story end to end."""
    store = Store(tmp_db)
    dag_id, tid = _submit_noop(store)
    Supervisor(store, worker_timeout_s=0.05).tick()

    env = dict(os.environ)
    env["MLCOMP_FAULTS"] = "worker.before_finish:kill:1"
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONPATH", os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_WORKER, tmp_db],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 137, proc.stderr
    assert "survived" not in proc.stdout
    assert store.task_statuses(dag_id)["t"] == TaskStatus.IN_PROGRESS

    time.sleep(0.1)
    sup = Supervisor(store, worker_timeout_s=0.05)
    sup.tick()
    assert store.task_statuses(dag_id)["t"] == TaskStatus.QUEUED

    from mlcomp_tpu.executors import load_all

    load_all()
    w = Worker(store, name="healthy", chips=0, load_jax_executors=False)
    assert w.run_once()
    assert sup.tick()[dag_id] == "success"
    store.close()


def test_parallel_readers_of_checkpoint_not_a_race():
    """ckpt_dir is a restore INPUT: val+test fan-out sharing one checkpoint
    must validate (regression: ckpt_dir was once treated as an output)."""
    tasks = [
        TaskSpec(name="train", executor="noop"),
        TaskSpec(name="val", executor="noop", depends=("train",),
                 args={"ckpt_dir": "ck/train"}),
        TaskSpec(name="test", executor="noop", depends=("train",),
                 args={"ckpt_dir": "ck/train"}),
    ]
    assert detect_write_races(tasks) == []
    validate_dag(DagSpec(name="d", project="p", tasks=tuple(tasks)))

"""Worker pool provisioner: inventory parsing, launch/restart/drain, and
the r3 verdict integration criterion — two localhost "hosts" provisioned
through the pool run a gang task end-to-end; killing a daemon mid-task
gets it relaunched and the task retried to success."""

import os
import signal
import time

import pytest

from mlcomp_tpu.dag.schema import DagSpec, ResourceSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.pool import (
    LOCAL_TEMPLATE,
    REMOTE_TEMPLATE,
    WorkerPool,
    parse_inventory,
)
from mlcomp_tpu.scheduler.supervisor import Supervisor


def test_parse_inventory():
    text = """
    # fleet
    localhost chips=4
    tpu-vm-0 workdir=/mnt/w zone=us-central2
    """
    hosts = parse_inventory(text, default_chips=1)
    assert hosts[0].host == "localhost" and hosts[0].chips == 4
    assert hosts[1].host == "tpu-vm-0" and hosts[1].chips == 1
    assert hosts[1].workdir == "/mnt/w"
    assert hosts[1].attrs == {"zone": "us-central2"}
    with pytest.raises(ValueError, match="key=value"):
        parse_inventory("h bad-attr")
    with pytest.raises(ValueError, match="at least one"):
        WorkerPool(None, [])


def test_default_templates_pick_by_host(tmp_path, tmp_db):
    store = Store(tmp_db)
    try:
        pool = WorkerPool(
            store,
            parse_inventory("localhost chips=2\ntpu-vm-3"),
            base_workdir=str(tmp_path),
        )
        local_cmd = " ".join(pool._render(pool._members[0]))
        remote_cmd = " ".join(pool._render(pool._members[1]))
        assert "ssh" not in local_cmd and "--chips 2" in local_cmd
        assert remote_cmd.startswith("ssh -o BatchMode=yes tpu-vm-3 ")
        assert "pool-1-tpu-vm-3" in remote_cmd
        assert "{" not in local_cmd + remote_cmd  # every placeholder filled
    finally:
        store.close()


def _submit_gang_sleep_dag(store, helper_dir, sleep_s, name="pool-mh"):
    helper = helper_dir / "pool_helper.py"
    helper.parent.mkdir(parents=True, exist_ok=True)
    helper.write_text(
        "import time\n"
        "def check(ctx):\n"
        f"    time.sleep({sleep_s})\n"
        "    import jax\n"
        "    assert jax.process_count() == 2\n"
        "    return {'processes': jax.process_count()}\n"
    )
    dag = DagSpec(
        name=name, project="t",
        tasks=(TaskSpec(
            name="mh", executor="pyfunc",
            args={
                "target": "pool_helper:check",
                "code_src": str(helper.parent),
                "code_import": [],
            },
            resources=ResourceSpec(hosts=2),
            max_retries=1,
        ),),
    )
    dag_id = store.submit_dag(dag)
    store.set_task_status(dag_id, ["mh"], TaskStatus.QUEUED)
    return dag_id, store.task_rows(dag_id)[0]["id"]


def test_pool_provisions_gang_restarts_dead_daemon(tmp_path, tmp_db):
    """Two localhost daemons via the pool; a hosts=2 gang task runs; one
    daemon is SIGKILLed mid-task; the pool relaunches it and the retried
    task completes."""
    store = Store(tmp_db)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        "PYTHONPATH": os.pathsep.join(
            p for p in (repo_root, os.environ.get("PYTHONPATH")) if p
        ),
    }
    pool = WorkerPool(
        store,
        parse_inventory("localhost\nlocalhost"),
        base_workdir=str(tmp_path / "pool"),
        heartbeat_timeout_s=20.0,
        restart_backoff_s=0.2,
        env=env,
    )
    sup = Supervisor(store, worker_timeout_s=12.0)
    dag_id, tid = _submit_gang_sleep_dag(store, tmp_path / "src", sleep_s=25)

    killed = {}

    def babysit(deadline, until):
        while time.time() < deadline:
            pool.poll_once()
            sup.tick()
            if until():
                return True
            time.sleep(0.4)
        return False

    try:
        # phase 1: daemons come up, the gang launches, the task runs
        assert babysit(
            time.time() + 180,
            lambda: store.task_row(tid)["status"]
            == TaskStatus.IN_PROGRESS.value,
        ), f"task never started: {store.task_row(tid)}"
        assert pool.alive_count() == 2

        # phase 2: SIGKILL one daemon mid-task (the task sleeps 25 s)
        victim = pool._members[0]["proc"]
        killed["pid"] = victim.pid
        os.kill(victim.pid, signal.SIGKILL)
        assert babysit(
            time.time() + 60,
            lambda: pool.alive_count() == 2
            and pool._members[0]["proc"].pid != killed["pid"],
        ), "dead daemon was not relaunched"
        assert pool._members[0]["restarts"] >= 1

        # phase 3: the reaped task retries on the refreshed pool and
        # completes
        assert babysit(
            time.time() + 240,
            lambda: store.task_row(tid)["status"]
            in (TaskStatus.SUCCESS.value, TaskStatus.FAILED.value),
        ), f"task never finished: {store.task_row(tid)}"
        row = store.task_row(tid)
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert row["status"] == TaskStatus.SUCCESS.value, (
            f"error={row['error']}\nlogs:\n{logs}"
        )
        assert row["retries"] >= 1, "the killed attempt should consume a retry"
    finally:
        pool.drain(timeout_s=30)
        store.close()
    assert pool.alive_count() == 0

"""Worker pool provisioner: inventory parsing, launch/restart/drain, and
the r3 verdict integration criterion — two localhost "hosts" provisioned
through the pool run a gang task end-to-end; killing a daemon mid-task
gets it relaunched and the task retried to success."""

import os
import signal
import time

import pytest

from mlcomp_tpu.dag.schema import DagSpec, ResourceSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.pool import (
    LOCAL_TEMPLATE,
    REMOTE_TEMPLATE,
    WorkerPool,
    parse_inventory,
)
from mlcomp_tpu.scheduler.supervisor import Supervisor


def test_parse_inventory():
    text = """
    # fleet
    localhost chips=4
    tpu-vm-0 workdir=/mnt/w zone=us-central2
    """
    hosts = parse_inventory(text, default_chips=1)
    assert hosts[0].host == "localhost" and hosts[0].chips == 4
    assert hosts[1].host == "tpu-vm-0" and hosts[1].chips == 1
    assert hosts[1].workdir == "/mnt/w"
    assert hosts[1].attrs == {"zone": "us-central2"}
    with pytest.raises(ValueError, match="key=value"):
        parse_inventory("h bad-attr")
    with pytest.raises(ValueError, match="at least one"):
        WorkerPool(None, [])


def test_default_templates_pick_by_host(tmp_path, tmp_db):
    store = Store(tmp_db)
    try:
        pool = WorkerPool(
            store,
            parse_inventory("localhost chips=2\ntpu-vm-3"),
            base_workdir=str(tmp_path),
        )
        local_cmd = " ".join(pool._render(pool._members[0]))
        remote_cmd = " ".join(pool._render(pool._members[1]))
        assert "ssh" not in local_cmd and "--chips 2" in local_cmd
        assert remote_cmd.startswith("ssh -o BatchMode=yes tpu-vm-3 ")
        assert "pool-1-tpu-vm-3" in remote_cmd
        assert "{" not in local_cmd + remote_cmd  # every placeholder filled
    finally:
        store.close()


def _submit_gang_sleep_dag(store, helper_dir, sleep_s, name="pool-mh"):
    helper = helper_dir / "pool_helper.py"
    helper.parent.mkdir(parents=True, exist_ok=True)
    helper.write_text(
        "import time\n"
        "def check(ctx):\n"
        f"    time.sleep({sleep_s})\n"
        "    import jax\n"
        "    assert jax.process_count() == 2\n"
        "    return {'processes': jax.process_count()}\n"
    )
    dag = DagSpec(
        name=name, project="t",
        tasks=(TaskSpec(
            name="mh", executor="pyfunc",
            args={
                "target": "pool_helper:check",
                "code_src": str(helper.parent),
                "code_import": [],
            },
            resources=ResourceSpec(hosts=2),
            max_retries=1,
        ),),
    )
    dag_id = store.submit_dag(dag)
    store.set_task_status(dag_id, ["mh"], TaskStatus.QUEUED)
    return dag_id, store.task_rows(dag_id)[0]["id"]


def test_pool_provisions_gang_restarts_dead_daemon(tmp_path, tmp_db):
    """Two localhost daemons via the pool; a hosts=2 gang task runs; one
    daemon is SIGKILLed mid-task; the pool relaunches it and the retried
    task completes."""
    store = Store(tmp_db)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        "PYTHONPATH": os.pathsep.join(
            p for p in (repo_root, os.environ.get("PYTHONPATH")) if p
        ),
    }
    pool = WorkerPool(
        store,
        parse_inventory("localhost\nlocalhost"),
        base_workdir=str(tmp_path / "pool"),
        heartbeat_timeout_s=20.0,
        restart_backoff_s=0.2,
        env=env,
    )
    sup = Supervisor(store, worker_timeout_s=12.0)
    # long enough that the SIGKILL below lands mid-task even on a
    # slow box (the IN_PROGRESS gate fires within one babysit tick,
    # ~0.4 s), short enough that the retry's full re-run does not
    # dominate the tier-1 budget
    dag_id, tid = _submit_gang_sleep_dag(store, tmp_path / "src", sleep_s=12)

    killed = {}

    def babysit(deadline, until):
        while time.time() < deadline:
            pool.poll_once()
            sup.tick()
            if until():
                return True
            time.sleep(0.4)
        return False

    try:
        # phase 1: daemons come up, the gang launches, the task runs
        assert babysit(
            time.time() + 180,
            lambda: store.task_row(tid)["status"]
            == TaskStatus.IN_PROGRESS.value,
        ), f"task never started: {store.task_row(tid)}"
        assert pool.alive_count() == 2

        # phase 2: SIGKILL one daemon mid-task (the task sleeps 25 s)
        victim = pool._members[0]["proc"]
        killed["pid"] = victim.pid
        os.kill(victim.pid, signal.SIGKILL)
        assert babysit(
            time.time() + 60,
            lambda: pool.alive_count() == 2
            and pool._members[0]["proc"].pid != killed["pid"],
        ), "dead daemon was not relaunched"
        assert pool._members[0]["restarts"] >= 1

        # phase 3: the reaped task retries on the refreshed pool and
        # completes
        assert babysit(
            time.time() + 240,
            lambda: store.task_row(tid)["status"]
            in (TaskStatus.SUCCESS.value, TaskStatus.FAILED.value),
        ), f"task never finished: {store.task_row(tid)}"
        row = store.task_row(tid)
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert row["status"] == TaskStatus.SUCCESS.value, (
            f"error={row['error']}\nlogs:\n{logs}"
        )
        assert row["retries"] >= 1, "the killed attempt should consume a retry"
    finally:
        pool.drain(timeout_s=30)
        store.close()
    assert pool.alive_count() == 0


def _pid_alive(pid: int) -> bool:
    """True when the pid is a LIVE process (zombies don't count — the
    detached fake daemon reparents to init and may linger as a zombie
    after the kill)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0] != "Z"
    except (FileNotFoundError, ProcessLookupError, IndexError):
        return False


def test_pool_remote_kill_reaches_detached_daemon(tmp_path, tmp_db):
    """r3 verdict weak#3: for a remote host the pool's process handle is
    only the ssh TRANSPORT — killing it leaves a wedged remote daemon
    claiming under the same name while its replacement starts.  Fake the
    topology locally: the launch template starts a transport that spawns
    a DETACHED never-heartbeating daemon; the kill template (the remote
    pkill stand-in) must reach the daemon itself, BEFORE the relaunch."""
    import subprocess
    import sys as _sys

    piddir = tmp_path / "pids"
    piddir.mkdir()
    transport = tmp_path / "transport.py"
    transport.write_text(
        "import subprocess, sys, time, os\n"
        "name = sys.argv[sys.argv.index('--name') + 1]\n"
        "piddir = sys.argv[sys.argv.index('--piddir') + 1]\n"
        # the daemon: detached (new session), tagged with the worker name,
        # never heartbeats -> the pool must see it as wedged
        "p = subprocess.Popen([sys.executable, '-c',\n"
        "    'import time\\nwhile True: time.sleep(1)', name],\n"
        "    start_new_session=True)\n"
        "open(os.path.join(piddir, name + '.pid'), 'w').write(str(p.pid))\n"
        "while True:\n"
        "    time.sleep(1)\n"
    )
    killer = tmp_path / "killer.py"
    killer.write_text(
        "import os, signal, sys\n"
        "name = sys.argv[sys.argv.index('--name') + 1]\n"
        "piddir = sys.argv[sys.argv.index('--piddir') + 1]\n"
        "sig = getattr(signal, 'SIG' + sys.argv[sys.argv.index('--signal') + 1])\n"
        "try:\n"
        "    pid = int(open(os.path.join(piddir, name + '.pid')).read())\n"
        "    os.kill(pid, sig)\n"
        "except (FileNotFoundError, ProcessLookupError):\n"
        "    sys.exit(1)\n"
    )
    store = Store(tmp_db)
    pool = WorkerPool(
        store,
        parse_inventory("fakeremote"),
        base_workdir=str(tmp_path / "pool"),
        launch_template=(
            "{python} " + str(transport) + " --name {name} --piddir "
            + str(piddir)
        ),
        kill_template=(
            "{python} " + str(killer) + " --name {name} --signal {signal}"
            " --piddir " + str(piddir)
        ),
        heartbeat_timeout_s=0.5,
        restart_backoff_s=0.05,
    )
    name = pool._members[0]["name"]
    pidfile = piddir / f"{name}.pid"
    try:
        assert pool.poll_once() == 1
        deadline = time.time() + 10
        while not pidfile.exists() and time.time() < deadline:
            time.sleep(0.05)
        pid1 = int(pidfile.read_text())
        assert _pid_alive(pid1)
        time.sleep(1.2)  # uptime > 2 * heartbeat_timeout: wedge window
        restarted = 0
        deadline = time.time() + 10
        while restarted == 0 and time.time() < deadline:
            restarted = pool.poll_once()
            time.sleep(0.05)
        assert restarted == 1, "pool never relaunched the wedged member"
        # the DETACHED daemon is dead (not just the transport) and its
        # replacement is a different live process — no same-name pair
        deadline = time.time() + 10
        while _pid_alive(pid1) and time.time() < deadline:
            time.sleep(0.05)
        assert not _pid_alive(pid1), "old detached daemon survived the kill"
        deadline = time.time() + 10
        pid2 = pid1
        while pid2 == pid1 and time.time() < deadline:
            pid2 = int(pidfile.read_text() or pid1)
            time.sleep(0.05)
        assert pid2 != pid1 and _pid_alive(pid2)
    finally:
        pool.drain(timeout_s=5.0)
        store.close()
    # drain's TERM kill-template pass reaches the detached daemon too
    deadline = time.time() + 10
    pid_last = int(pidfile.read_text())
    while _pid_alive(pid_last) and time.time() < deadline:
        time.sleep(0.05)
    assert not _pid_alive(pid_last)


def test_remote_kill_template_pattern_precise_and_self_safe():
    """r4 advisor (medium): the pkill -f pattern must (a) anchor the
    worker name — 'host-1' must not SIGKILL 'host-11' — and (b) never
    match the remote shell / pkill's OWN command line (self-match makes
    ssh report a spurious nonzero even when the kill worked)."""
    import re
    import shlex

    from mlcomp_tpu.scheduler.pool import (
        LOCAL_TEMPLATE, REMOTE_KILL_TEMPLATE,
    )

    local_args = shlex.split(REMOTE_KILL_TEMPLATE.format(
        host="h", signal="KILL", name="host-1",
    ))
    # ssh joins the remote words with spaces and hands them to sh -c;
    # the inner single quotes must survive to keep ( | $ ) shell-safe
    remote_cmd = " ".join(local_args[4:])
    remote_args = shlex.split(remote_cmd)  # the remote shell's parse
    assert remote_args[:3] == ["pkill", "-KILL", "-f"]
    pattern = remote_args[-1]
    assert "'" not in pattern  # quotes consumed by the remote shell

    daemon = LOCAL_TEMPLATE.format(
        python="python", db="/d.sqlite", name="host-1", chips=0,
        workdir="/w",
    )
    other = LOCAL_TEMPLATE.format(
        python="python", db="/d.sqlite", name="host-11", chips=0,
        workdir="/w",
    )
    assert re.search(pattern, daemon)
    assert not re.search(pattern, other), "prefix name over-matched"
    # custom launch templates may render '--name={name}' (argparse
    # accepts both separators); the default kill pattern must cover it
    assert re.search(pattern, daemon.replace("--name host-1", "--name=host-1"))
    # pkill -f matches against full command lines INCLUDING its own and
    # its parent shell's, both of which contain the pattern text
    assert not re.search(pattern, remote_cmd), "pattern matched its own cmdline"
    assert not re.search(pattern, "sh -c " + shlex.quote(remote_cmd))

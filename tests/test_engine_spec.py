"""Speculative engine dispatch (DecodeEngine(spec_k=...)): greedy
equality with bare generate across cache modes, mid-decode join, eos,
budget, and the greedy-only submit gate."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mlcomp_tpu.engine import DecodeEngine
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.train.state import init_model


def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


def _reference(model, params, ids, n_new, bucket=16, **kw):
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask), **kw,
    )
    return np.asarray(out)[0, bucket:].tolist()


@pytest.mark.parametrize("kv_quant", [False, True])
def test_spec_engine_greedy_matches_generate(kv_quant):
    model, params = _model_and_params(kv_quant)
    eng = DecodeEngine(model, {"params": params}, slots=4,
                       prompt_buckets=(16,), max_new_cap=8, spec_k=3)
    try:
        rs = np.random.RandomState(1)
        prompts = [rs.randint(1, 64, n).tolist() for n in (5, 9, 13)]
        futs = [eng.submit(p, 6) for p in prompts]
        for p, f in zip(prompts, futs):
            got = f.result(timeout=300)
            assert got["ids"] == _reference(model, params, p, 6), p
        st = eng.stats()
        assert st["dispatches"] >= 1
    finally:
        eng.close()


def test_spec_engine_eos_budget_and_logprobs():
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8, spec_k=4)
    try:
        p = [7, 3, 21, 9]
        free = eng.submit(p, 8, logprobs=True).result(timeout=300)
        assert len(free["ids"]) == 8
        prompt = np.full((1, 16), 0, np.int32)
        mask = np.zeros((1, 16), bool)
        prompt[0, 16 - len(p):] = p
        mask[0, 16 - len(p):] = True
        rids, rlps = generate(
            model, {"params": params}, jnp.asarray(prompt), 8,
            prompt_mask=jnp.asarray(mask), with_logprobs=True,
        )
        assert free["ids"] == np.asarray(rids)[0, 16:].tolist()
        np.testing.assert_allclose(
            free["logprobs"], np.asarray(rlps)[0], atol=1e-3
        )
        # eos mid-stream stops the row exactly like generate
        eos = free["ids"][3]
        got = eng.submit(p, 8, eos_id=eos).result(timeout=300)
        want = _reference(model, params, p, 8, eos_id=eos)
        # the engine emits up to AND including eos (no trailing pads)
        assert got["ids"] == want[: want.index(eos) + 1]
        # budget smaller than spec_k still exact
        got2 = eng.submit(p, 2).result(timeout=300)
        assert got2["ids"] == free["ids"][:2]
    finally:
        eng.close()


def test_spec_engine_mid_decode_join():
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8, spec_k=3)
    try:
        rs = np.random.RandomState(5)
        a = rs.randint(1, 64, 6).tolist()
        fa = eng.submit(a, 8)
        while eng.stats()["dispatches"] < 1:  # a is mid-decode
            pass
        b = rs.randint(1, 64, 10).tolist()
        fb = eng.submit(b, 8)
        assert fa.result(timeout=300)["ids"] == _reference(
            model, params, a, 8
        )
        assert fb.result(timeout=300)["ids"] == _reference(
            model, params, b, 8
        )
    finally:
        eng.close()


def test_spec_engine_rejects_sampling_and_mesh():
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8, spec_k=3)
    try:
        with pytest.raises(ValueError, match="greedy-only"):
            eng.submit([1, 2], 4, temperature=0.8)
        with pytest.raises(ValueError, match="greedy-only"):
            eng.submit([1, 2], 4, repetition_penalty=1.3)
    finally:
        eng.close()
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(model, {"params": params}, spec_k=0)


@pytest.mark.parametrize("spec_k", [None, 3])
def test_engine_buffer_edge_rows_stay_exact(spec_k):
    """A max-bucket prompt running its FULL budget sits exactly at the
    buffer edge — where a retired row's frozen-cursor write would
    clamp onto its last real K/V without the engine's scratch slot
    (round-5 DUS semantics).  Outputs must stay exact while other rows
    keep decoding past the retirement."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8,
                       spec_k=spec_k)
    try:
        rs = np.random.RandomState(11)
        full = rs.randint(1, 64, 16).tolist()   # fills the top bucket
        short = rs.randint(1, 64, 5).tolist()
        fa = eng.submit(full, 8)                # retires at the edge
        fb = eng.submit(short, 8)
        assert fa.result(timeout=300)["ids"] == _reference(
            model, params, full, 8
        )
        assert fb.result(timeout=300)["ids"] == _reference(
            model, params, short, 8
        )
        # a second wave reuses the freed slots (insert overwrites any
        # scratch-slot leftovers)
        again = eng.submit(full, 8).result(timeout=300)
        assert again["ids"] == _reference(model, params, full, 8)
    finally:
        eng.close()


def test_spec_engine_quant_kernel_matches_generate():
    from mlcomp_tpu.ops.quant import quantize_params

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 1, "heads": 2, "mlp_dim": 512, "dtype": "float32",
        "kv_quant": True,
    })
    prompt = jnp.asarray(np.random.RandomState(7).randint(1, 128, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    eng = DecodeEngine(model, q, slots=2, prompt_buckets=(16,),
                       max_new_cap=6, quant_kernel=True, spec_k=3)
    try:
        p = np.random.RandomState(8).randint(1, 128, 9).tolist()
        got = eng.submit(p, 6).result(timeout=600)
        prompt_row = np.full((1, 16), 0, np.int32)
        mask = np.zeros((1, 16), bool)
        prompt_row[0, 16 - len(p):] = p
        mask[0, 16 - len(p):] = True
        ref = generate(
            model, q, jnp.asarray(prompt_row), 6,
            prompt_mask=jnp.asarray(mask), quant_kernel=True,
        )
        assert got["ids"] == np.asarray(ref)[0, 16:].tolist()
    finally:
        eng.close()


def test_spec_engine_warns_on_dead_steps_per_dispatch():
    """ADVICE r5: spec_k replaces the K-step scan, so an explicit
    steps_per_dispatch != 1 is a dead knob — the constructor says so.
    The default (None) resolves to 1 for spec engines and stays
    silent."""
    import warnings

    model, params = _model_and_params()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # default must NOT warn
        eng = DecodeEngine(model, {"params": params}, slots=2,
                           prompt_buckets=(16,), max_new_cap=8, spec_k=2)
    assert eng.steps_per_dispatch == 1
    eng.close()
    with pytest.warns(UserWarning, match="ignore steps_per_dispatch"):
        eng = DecodeEngine(model, {"params": params}, slots=2,
                           prompt_buckets=(16,), max_new_cap=8,
                           spec_k=2, steps_per_dispatch=4)
    eng.close()


def test_spec_engine_warns_past_gemv_row_budget():
    """r5 verdict weak #3: slots*(spec_k+1) > _GEMV_ROWS drops the int8
    verify onto prefill blocks (~2x per-call) — the constructor warns
    instead of leaving the cliff in a comment.  Within budget (8*8=64)
    stays silent."""
    import warnings

    from mlcomp_tpu.ops.quant import quantize_params

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 1, "heads": 2, "mlp_dim": 512, "dtype": "float32",
        "kv_quant": True,
    })
    prompt = jnp.asarray(np.random.RandomState(7).randint(1, 128, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    with pytest.warns(UserWarning, match="fat-block"):
        eng = DecodeEngine(model, q, slots=8, prompt_buckets=(16,),
                           max_new_cap=6, quant_kernel=True, spec_k=8)
    eng.close()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        eng = DecodeEngine(model, q, slots=8, prompt_buckets=(16,),
                           max_new_cap=6, quant_kernel=True, spec_k=7)
    eng.close()
    # no int8 kernel -> no cliff -> no warning however big the product
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        eng = DecodeEngine(model, {"params": params}, slots=8,
                           prompt_buckets=(16,), max_new_cap=6, spec_k=8)
    eng.close()


def test_spec_net_gain_surfaced_and_pure_loss_warns_once():
    """Spec honesty (BENCH_r05: acceptance_tokens_per_row 1.0 while the
    knob cost throughput): a spec engine's stats() carries a "spec"
    block with the measured acceptance and spec_net_gain (<= 0 = pure
    loss), the service lifts it to the top level for /healthz, and the
    engine warns EXACTLY once when measured acceptance makes
    speculation a loss."""
    import warnings

    from mlcomp_tpu.serve import GenerationService

    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8, spec_k=3)
    try:
        futs = [eng.submit([5, 6, 7, 8], 6), eng.submit([9, 2, 4], 6)]
        for f in futs:
            f.result(timeout=300)
        st = eng.stats()
        spec = st["spec"]
        assert spec["spec_k"] == 3
        assert spec["acceptance_tokens_per_row"] >= 1.0
        assert spec["spec_net_gain"] == pytest.approx(
            spec["acceptance_tokens_per_row"] - 1.0, abs=1e-3
        )
        # deterministic pure-loss verdict: pin the counters at the
        # warning threshold (traffic-dependent acceptance can't be
        # forced from outside) and check the one-shot behavior
        eng._spec_warned = False
        eng._stats["spec_rows"] = 64
        eng._stats["emitted_tokens"] = 64          # acceptance == 1.0
        with pytest.warns(UserWarning, match="net LOSS"):
            eng._maybe_warn_spec_loss()
        with warnings.catch_warnings():
            warnings.simplefilter("error")         # second call: silent
            eng._maybe_warn_spec_loss()
        assert eng.stats()["spec"]["spec_net_gain"] == 0.0
    finally:
        eng.close()
    # non-spec engines carry no spec block; the service only lifts it
    # when present
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8)
    try:
        assert "spec" not in eng.stats()
    finally:
        eng.close()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,), engine_spec_k=2,
    )
    try:
        svc.generate([5, 6, 7], 4)
        st = svc.stats()
        assert st["spec"] is st["engine"]["spec"]
        assert "spec_net_gain" in st["spec"]
    finally:
        svc.close()

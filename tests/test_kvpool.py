"""Unit coverage for the device KV page pool (mlcomp_tpu/kvpool):
allocator free-list/ref-count bookkeeping, slot-row composition with
copy-on-write forks, the device prefix-page registry, the paged
layout's gather/scatter round trip (bit-exact on both cache families,
lax and Pallas-interpret gathers), and a fragmentation churn stress
asserting zero leaked pages at quiesce."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.kvpool import (
    GRAVE_PAGE,
    NULL_PAGE,
    RESERVED_PAGES,
    NoFreePages,
    PageAllocator,
    PagedLayout,
    PagePool,
)

# ------------------------------------------------------------ allocator


def test_allocator_lifecycle():
    a = PageAllocator(num_pages=10, page_tokens=4)
    assert a.total_pages == 8 and a.free_pages == 8
    got = a.alloc(3)
    assert len(got) == 3 and a.free_pages == 5 and a.used_pages == 3
    assert all(p >= RESERVED_PAGES for p in got)
    assert all(a.refs(p) == 1 for p in got)
    # retain/release ref-count: last release frees
    a.retain(got[0])
    assert a.refs(got[0]) == 2
    assert a.release(got[0]) is False
    assert a.release(got[0]) is True
    assert a.free_pages == 6
    a.check_invariants()
    # reserved pages are permanently pinned no-ops
    a.retain(NULL_PAGE)
    assert a.release(GRAVE_PAGE) is False
    # misuse raises instead of corrupting the books
    with pytest.raises(ValueError):
        a.release(got[0])  # already freed
    with pytest.raises(ValueError):
        a.retain(9)  # never allocated


def test_allocator_all_or_nothing():
    a = PageAllocator(num_pages=6, page_tokens=4)  # 4 allocatable
    a.alloc(3)
    free0 = a.free_pages
    with pytest.raises(NoFreePages):
        a.alloc(2)
    # the failed grab took NOTHING off the free list
    assert a.free_pages == free0
    assert a.counters["failed_allocs"] == 1
    a.check_invariants()


def test_allocator_lifo_reuse():
    a = PageAllocator(num_pages=8, page_tokens=4)
    (p,) = a.alloc(1)
    a.release(p)
    assert a.alloc(1) == [p]  # hottest page re-used first


# --------------------------------------------------------------- pool


def _pool(num_pages=18, page_tokens=4, l_buf=24, max_slots=4):
    class _Layout:  # geometry-only stand-in (no JAX)
        pass

    lay = _Layout()
    lay.num_pages = num_pages
    lay.page_tokens = page_tokens
    lay.max_pages = -(-l_buf // page_tokens)
    lay.page_bytes = lambda: 1024
    return PagePool(lay, max_slots=max_slots)


def test_slot_row_pads_cost_nothing():
    pool = _pool()
    # real span [10, 21): page 2 (8..12) .. page 5 (20..24) — pages 0-1
    # sit fully inside the pad prefix and stay NULL
    assert pool.pages_needed(10, 21) == 4
    row, mask, forks = pool.build_slot_row(10, 21)
    assert forks == 0
    assert list(row[:2]) == [NULL_PAGE, NULL_PAGE]
    assert all(p >= RESERVED_PAGES for p in row[2:6])
    assert list(row[6:]) == [NULL_PAGE] * (pool.max_pages - 6)
    assert list(mask[2:6]) == [True] * 4 and not mask[:2].any()
    pool.commit_slot_row(0, row)
    pool.check_invariants()
    pool.free_slot(0)
    assert pool.alloc.free_pages == pool.alloc.total_pages
    assert (pool.tables[0] == GRAVE_PAGE).all()
    pool.check_invariants()


def test_registry_share_and_cow_fork():
    pool = _pool()
    T = pool.page_tokens
    s_bucket, start_pad = 16, 6
    ids = list(range(100, 110))  # 10 real tokens
    row, mask, _ = pool.build_slot_row(start_pad, 21)
    pool.commit_slot_row(0, row)
    assert pool.registry_register(s_bucket, start_pad, ids, row) is True
    # same prompt again: idempotent (retry storm), no duplicate pin
    assert pool.registry_register(s_bucket, start_pad, ids, row) is False
    # a second request sharing the full prompt at the same placement
    lease = pool.registry_lookup(s_bucket, start_pad, ids)
    assert lease is not None and lease.matched == 10
    # boundary: shared span capped at the entry's page-aligned end
    assert lease.boundary == s_bucket
    row2, mask2, forks2 = pool.build_slot_row(start_pad, 21, shared=lease)
    # pages fully below the boundary are SHARED (same physical ids)
    n_shared = s_bucket // T - start_pad // T
    for p in range(start_pad // T, s_bucket // T):
        assert row2[p] == row[p] and not mask2[p]
        assert pool.alloc.refs(int(row[p])) >= 2
    assert forks2 == 0 and pool.counters["shared_mappings"] == n_shared
    pool.commit_slot_row(1, row2)
    lease.release()
    pool.check_invariants()
    # DIVERGENT suffix: matched stops mid-page -> the boundary page
    # forks a private copy (counted), earlier full pages still share
    ids3 = ids[:9] + [999]
    lease3 = pool.registry_lookup(s_bucket, start_pad, ids3)
    assert lease3 is not None and lease3.matched == 9
    # slot coords: shared boundary 6+9=15 lands inside page 3 (12..16)
    row3, mask3, forks3 = pool.build_slot_row(start_pad, 21, shared=lease3)
    assert forks3 == 1 and pool.alloc.counters["cow_forks"] == 1
    assert row3[2] == row[2]           # full page below 15: shared
    assert row3[3] != row[3] and mask3[3]  # the fork: private + written
    pool.release_row(row3)
    lease3.release()
    pool.check_invariants()


def test_registry_lru_reclaim_and_lease_pinning():
    pool = _pool(num_pages=18)
    rows = []
    for i in range(3):
        ids = [200 + 10 * i + j for j in range(10)]
        row, _, _ = pool.build_slot_row(6, 21)
        pool.commit_slot_row(i, row)
        pool.registry_register(16, 6, ids, row)
        rows.append((i, ids, row))
    for i, _, _ in rows:
        pool.free_slot(i)  # only registry pins remain
    pinned0 = pool.alloc.used_pages
    assert pinned0 > 0 and pool.reclaimable_pages() == pinned0
    # a LEASED entry survives reclaim even when its entry is evicted
    _, ids0, _ = rows[0]
    lease = pool.registry_lookup(16, 6, ids0)
    evicted = pool.reclaim_all()
    assert evicted == 3 and pool.registry_entries == 0
    assert pool.alloc.used_pages > 0  # the lease still pins its pages
    lease.release()
    assert pool.alloc.free_pages == pool.alloc.total_pages
    pool.check_invariants()


def test_pool_churn_no_leaks():
    """Fragmentation stress: random admit/retire cycles with sharing —
    at quiesce (slots freed, registry flushed) free == total."""
    pool = _pool(num_pages=40, max_slots=6)
    rng = np.random.RandomState(0)
    live = {}
    for step in range(300):
        if live and (len(live) == pool.max_slots or rng.rand() < 0.45):
            slot = rng.choice(sorted(live))
            lease = live.pop(slot)
            pool.free_slot(slot)
            if lease is not None:
                lease.release()
        else:
            slot = next(
                i for i in range(pool.max_slots) if i not in live
            )
            n_ids = int(rng.randint(1, 16))
            ids = rng.randint(0, 5, size=n_ids).tolist()  # collisions
            start_pad = 16 - n_ids
            lease = pool.registry_lookup(16, start_pad, ids)
            try:
                row, _, _ = pool.build_slot_row(
                    start_pad, 17 + int(rng.randint(0, 7)), shared=lease
                )
            except NoFreePages:
                pool.reclaim_all()
                if lease is not None:
                    lease.release()
                continue
            pool.commit_slot_row(slot, row)
            pool.registry_register(16, start_pad, ids, row)
            live[slot] = lease
        if step % 50 == 0:
            pool.check_invariants()
    for slot, lease in live.items():
        pool.free_slot(slot)
        if lease is not None:
            lease.release()
    pool.reclaim_all()
    pool.check_invariants()
    assert pool.alloc.free_pages == pool.alloc.total_pages
    st = pool.stats()
    assert st["pages_used"] == 0 and st["outstanding_page_leases"] == 0


# -------------------------------------------------------------- layout


@functools.lru_cache(maxsize=None)
def _cache_family(kv_quant):
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import init_cache

    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    return model, init_cache


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("impl", ["lax", "pallas"])
def test_layout_roundtrip_bit_exact(kv_quant, impl):
    """scatter -> gather through a page table rebuilds the EXACT dense
    cache pytree (shapes, dtypes, bytes) on both cache families, with
    both gather implementations (Pallas in interpret mode on CPU)."""
    from mlcomp_tpu.kvpool import layout as layout_mod

    model, init_cache = _cache_family(kv_quant)
    l_buf, slots, T = 24, 2, 8
    cache_abs = jax.eval_shape(lambda: init_cache(model, 1, l_buf))
    # page count unset at construction, then sized to a fully-private
    # table (the kv8 family lane-rounds the buffer, widening max_pages)
    lay = PagedLayout(cache_abs, l_buf, T)
    lay.num_pages = RESERVED_PAGES + slots * lay.max_pages
    # a fully-mapped private table (every row span = whole buffer)
    table = np.full((slots, lay.max_pages), GRAVE_PAGE, np.int32)
    nxt = RESERVED_PAGES
    for s in range(slots):
        for p in range(lay.max_pages):
            table[s, p] = nxt
            nxt += 1
    table = jnp.asarray(table)
    # a deterministic non-trivial dense cache: iota-patterned leaves
    dense = init_cache(model, slots, l_buf)
    dense = jax.tree.map(
        lambda leaf: (
            jnp.arange(leaf.size, dtype=jnp.float32)
            .reshape(leaf.shape).astype(leaf.dtype)
            if leaf.ndim else leaf
        ),
        dense,
    )
    pages = lay.fresh_pages()
    scalars = lay.scalars_of(dense)
    pages2 = lay.scatter(pages, table, dense)
    if impl == "pallas":
        # interpret-mode Pallas gather (the TPU kernel's logic on CPU)
        rebuilt_leaves = []
        for spec, pg in zip(lay.kv_specs, pages2):
            rows = layout_mod._gather_leaf_pallas(
                np.asarray(pg), table, interpret=True
            )
            rebuilt_leaves.append(
                lay._rows_to_view(spec, jnp.asarray(rows))
            )
        ki = iter(rebuilt_leaves)
        si = iter(scalars)
        rebuilt = lay.treedef.unflatten([
            next(ki) if s.slot_axis is not None else next(si)
            for s in lay.leaves
        ])
    else:
        rebuilt = lay.gather(pages2, table, scalars, impl="lax")
    flat_a = jax.tree_util.tree_leaves(dense)
    flat_b = jax.tree_util.tree_leaves(rebuilt)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_layout_null_grave_semantics():
    """NULL-mapped positions gather zeros; a scatter through a table
    whose rows all map NULL/GRAVE leaves the zero page untouched for
    the content actually gathered from it (the structural invariant:
    every mapper writes back the zeros it read)."""
    model, init_cache = _cache_family(False)
    l_buf, T = 24, 8
    cache_abs = jax.eval_shape(lambda: init_cache(model, 1, l_buf))
    lay = PagedLayout(cache_abs, l_buf, T, num_pages=8)
    pages = lay.fresh_pages()
    table = jnp.full((1, lay.max_pages), NULL_PAGE, jnp.int32)
    dense = lay.gather(pages, table, lay.scalars_of(
        init_cache(model, 1, l_buf)
    ))
    for leaf in jax.tree_util.tree_leaves(dense):
        if leaf.ndim:
            assert not np.asarray(leaf).any()
    # round-trip the zeros: NULL stays all-zero
    pages2 = lay.scatter(pages, table, dense)
    for pg in pages2:
        assert not np.asarray(pg[NULL_PAGE]).any()


def test_layout_page_tokens_must_divide():
    model, init_cache = _cache_family(False)
    cache_abs = jax.eval_shape(lambda: init_cache(model, 1, 24))
    lay = PagedLayout(cache_abs, 24, 5, num_pages=12)
    # geometry only: max_pages covers the longest leaf buffer
    assert lay.max_pages >= -(-24 // 5)
    with pytest.raises(ValueError):
        PagedLayout(cache_abs, 24, 0, num_pages=12)


def test_build_slot_row_alloc_end_defers_decode_pages():
    """Lazy decode allocation: ``alloc_end`` bounds the pages built
    NOW (the tail stays NULL), and ``extend_slot_row`` grows the
    committed row all-or-nothing as the cursor approaches."""
    pool = _pool(num_pages=18, page_tokens=4, l_buf=24)
    # span [10, 21) = pages 2..5; alloc_end 17 backs only pages 2..4
    row, mask, _ = pool.build_slot_row(10, 21, alloc_end=17)
    assert all(p >= RESERVED_PAGES for p in row[2:5])
    assert row[5] == NULL_PAGE and not mask[5]
    pool.commit_slot_row(0, row)
    used0 = pool.alloc.used_pages
    row2 = pool.extend_slot_row(0, 5, 6)
    assert row2[5] >= RESERVED_PAGES
    assert pool.alloc.used_pages == used0 + 1
    assert (pool.tables[0] == row2).all()
    pool.check_invariants()
    # exhaustion is all-or-nothing: a failed extend changes nothing
    pool.alloc.alloc(pool.alloc.free_pages)
    with pytest.raises(NoFreePages):
        pool.extend_slot_row(0, 0, 1)  # pos 0 is NULL (pad prefix)
    assert pool.tables[0][0] == NULL_PAGE
    # private_pages_needed honors the same bound
    assert pool.private_pages_needed(10, 21, alloc_end=17) == 3
    assert pool.private_pages_needed(10, 21) == 4


@pytest.mark.parametrize("chunk", [False, True])
def test_paged_kernel_bit_exact_vs_dense(chunk):
    """The paged Pallas kernels (interpret mode) against the dense
    kernels on the same cache bytes scattered into pages through a
    permuted table: BIT-exact, with NULL pages outside the windows
    skipped and unmapped pages poisoned with NaN scale bytes (a
    skipped page's garbage must never reach the accumulator — the
    interpret-mode unit that catches in-kernel DMA/masking bugs the
    engine matrix would only surface as diverged tokens)."""
    from mlcomp_tpu.ops.pallas.decode_attention import (
        decode_attention,
        decode_attention_chunk,
        paged_decode_attention,
        paged_decode_attention_chunk,
        quantize_kv,
    )

    rng = np.random.RandomState(0)
    B, H, HKV, DH, L, T = 2, 4, 2, 128, 128, 32
    MP = L // T
    k8, ks = quantize_kv(jnp.asarray(
        rng.randn(B, HKV, L, DH).astype(np.float32)
    ))
    v8, vs = quantize_kv(jnp.asarray(
        rng.randn(B, HKV, L, DH).astype(np.float32)
    ))
    ks4 = ks[:, :, None, :].astype(jnp.bfloat16)
    vs4 = vs[:, :, None, :].astype(jnp.bfloat16)
    start = jnp.asarray(np.array([5, 40], np.int32))
    # pages: permuted physical placement; UNMAPPED pages poisoned
    P = RESERVED_PAGES + B * MP
    perm = rng.permutation(B * MP)
    table = np.zeros((B, MP), np.int32)
    kqp = np.zeros((P, HKV, T, DH), np.int8)
    vqp = np.zeros((P, HKV, T, DH), np.int8)
    ksp = np.full((P, HKV, 1, T), np.nan, np.float32)
    vsp = np.full((P, HKV, 1, T), np.nan, np.float32)
    k8n, v8n = np.asarray(k8), np.asarray(v8)
    ks4n = np.asarray(ks4.astype(jnp.float32))
    vs4n = np.asarray(vs4.astype(jnp.float32))
    for b in range(B):
        for p in range(MP):
            pid = RESERVED_PAGES + int(perm[b * MP + p])
            table[b, p] = pid
            kqp[pid] = k8n[b, :, p * T:(p + 1) * T, :]
            vqp[pid] = v8n[b, :, p * T:(p + 1) * T, :]
            ksp[pid] = ks4n[b, :, :, p * T:(p + 1) * T]
            vsp[pid] = vs4n[b, :, :, p * T:(p + 1) * T]
    pages = (jnp.asarray(kqp), jnp.asarray(ksp).astype(jnp.bfloat16),
             jnp.asarray(vqp), jnp.asarray(vsp).astype(jnp.bfloat16))
    if chunk:
        S = 3
        q = jnp.asarray(rng.randn(B, S, H, DH).astype(np.float32))
        stop0 = jnp.asarray(np.array([100, 41], np.int32))
        dense = decode_attention_chunk(
            q, k8, ks4, v8, vs4, kv_start=start, kv_stop0=stop0
        )
        paged = paged_decode_attention_chunk(
            q, *pages, jnp.asarray(table), kv_start=start,
            kv_stop0=stop0,
        )
    else:
        q = jnp.asarray(rng.randn(B, H, DH).astype(np.float32))
        stop = jnp.asarray(np.array([100, 41], np.int32))
        dense = decode_attention(
            q, k8, ks4, v8, vs4, kv_start=start, kv_stop=stop
        )
        # NULL out every page fully outside the window: the kernel
        # must skip them (no DMA) and still match
        tbl2 = table.copy()
        for b, (lo, hi) in enumerate(zip((5, 40), (100, 41))):
            for p in range(MP):
                if (p + 1) * T <= lo or p * T >= hi:
                    tbl2[b, p] = NULL_PAGE
        paged_null = paged_decode_attention(
            q, *pages, jnp.asarray(tbl2), kv_start=start, kv_stop=stop
        )
        np.testing.assert_array_equal(
            np.asarray(dense), np.asarray(paged_null)
        )
        paged = paged_decode_attention(
            q, *pages, jnp.asarray(table), kv_start=start, kv_stop=stop
        )
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(paged))


def test_paged_wide_chunk_fallback_matches_dense():
    """Chunk widths past CHUNK_MAX_SQ (spec_k >= 32) take the XLA
    dequant fallback on BOTH paths — dense reads its buffer, fused
    reads a table gather of identical bytes — and must stay bit-equal
    (the fallback is a hand-mirrored copy of chunk_attend's dense
    branch; this test is what keeps the two from drifting)."""
    from mlcomp_tpu.kvpool import PagedKV, paged_kv
    from mlcomp_tpu.ops.pallas.decode_attention import CHUNK_MAX_SQ

    model, init_cache = _cache_family(True)
    slots, l_buf, T = 2, 48, 4
    s = CHUNK_MAX_SQ + 1
    rng = np.random.RandomState(5)
    from mlcomp_tpu.train.state import init_model

    prompt = jnp.asarray(rng.randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(2))
    cache = init_cache(model, slots, l_buf)
    cache_abs = jax.eval_shape(lambda: init_cache(model, 1, l_buf))
    lay = PagedLayout(cache_abs, l_buf, T)
    lay.num_pages = RESERVED_PAGES + slots * lay.max_pages
    table = np.full((slots, lay.max_pages), GRAVE_PAGE, np.int32)
    nxt = RESERVED_PAGES
    for s_ in range(slots):
        for p in range(lay.max_pages):
            table[s_, p] = nxt
            nxt += 1
    table = jnp.asarray(table)
    pages = lay.scatter(lay.fresh_pages(), table, cache)

    tok = jnp.asarray(rng.randint(1, 64, (slots, s)))
    cur = jnp.asarray(np.array([2, 5], np.int32))
    pos = cur[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    kv_mask = jnp.ones((slots, l_buf), bool)

    def dense_step(cache_in):
        return model.apply(
            {"params": params, "cache": cache_in}, tok, decode=True,
            positions=pos, kv_mask=kv_mask, cache_cursor=cur,
            mutable=["cache"],
        )[0]

    def fused_step(pages_in):
        ctx = PagedKV(lay, pages_in, table, impl="auto")
        with paged_kv(ctx):
            logits, _ = model.apply(
                {"params": params}, tok, decode=True, positions=pos,
                kv_mask=kv_mask, cache_cursor=cur, mutable=["cache"],
            )
        return logits
    d = jax.jit(dense_step)(cache)
    f = jax.jit(fused_step)(pages)
    np.testing.assert_array_equal(np.asarray(d), np.asarray(f))


def test_insert_rows_routes_shared_to_grave():
    """insert_rows writes ONLY write-selected pages; entries routed to
    GRAVE (shared/NULL positions) leave their physical pages alone —
    the copy-on-write mapping is zero-copy by construction."""
    model, init_cache = _cache_family(False)
    l_buf, T = 24, 8
    cache_abs = jax.eval_shape(lambda: init_cache(model, 1, l_buf))
    lay = PagedLayout(cache_abs, l_buf, T, num_pages=10)
    pages = lay.fresh_pages()
    # pre-mark page 2 (the "shared prefix" page) with a sentinel
    pages = [pg.at[2].set(7.0) if pg.dtype == jnp.float32 else
             pg.at[2].set(7) for pg in pages]
    row = init_cache(model, 1, l_buf)
    row = jax.tree.map(
        lambda leaf: jnp.ones(leaf.shape, leaf.dtype)
        if leaf.ndim else leaf, row,
    )
    # slot maps [shared=2, private=3, private=4]; write_sel routes the
    # shared page to GRAVE
    wsel = jnp.asarray(np.array([GRAVE_PAGE, 3, 4], np.int32))
    out = lay.insert_rows(pages, wsel, row)
    for pg in out:
        sent = np.asarray(pg[2]).ravel()[0]
        assert sent == 7  # shared page untouched
        assert np.asarray(pg[3]).any()  # private pages got the bytes
        assert not np.asarray(pg[NULL_PAGE]).any()

"""Ulysses all-to-all sequence parallelism vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.ops.attention import reference_attention
from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh
from mlcomp_tpu.parallel.ulysses import ulysses_attention_sharded


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    mesh = make_mesh(MeshSpec(sp=8))
    q = _rand((2, 64, 8, 16), 0)
    k = _rand((2, 64, 8, 16), 1)
    v = _rand((2, 64, 8, 16), 2)
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_gqa():
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 32, 8, 16), 3)
    k = _rand((1, 32, 4, 16), 4)
    v = _rand((1, 32, 4, 16), 5)
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, causal=True)
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh(MeshSpec(sp=8))
    q = _rand((1, 32, 4, 16), 6)  # 4 heads < sp=8
    k = _rand((1, 32, 4, 16), 7)
    v = _rand((1, 32, 4, 16), 8)
    with pytest.raises(ValueError, match="ring attention"):
        jax.jit(
            lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, causal=True)
        )(q, k, v)


def test_ulysses_differentiable():
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 32, 4, 16), 9)
    k = _rand((1, 32, 4, 16), 10)
    v = _rand((1, 32, 4, 16), 11)

    def loss_uly(q, k, v):
        return jnp.sum(ulysses_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gu = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_ulysses_with_dp_and_tp():
    mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
    q = _rand((4, 32, 8, 16), 12)
    k = _rand((4, 32, 8, 16), 13)
    v = _rand((4, 32, 8, 16), 14)
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, causal=True)
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

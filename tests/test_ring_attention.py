"""Ring attention over the sp axis vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.ops.attention import reference_attention
from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh
from mlcomp_tpu.parallel.ring import ring_attention_sharded


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    mesh = make_mesh(MeshSpec(sp=8))
    q = _rand((2, 64, 4, 16), 0)
    k = _rand((2, 64, 4, 16), 1)
    v = _rand((2, 64, 4, 16), 2)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=causal)
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gqa():
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 32, 4, 16), 3)
    k = _rand((1, 32, 2, 16), 4)
    v = _rand((1, 32, 2, 16), 5)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=True))(
        q, k, v
    )
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_differentiable():
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 32, 2, 16), 6)
    k = _rand((1, 32, 2, 16), 7)
    v = _rand((1, 32, 2, 16), 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_chunked_kv_matches_reference(monkeypatch, causal):
    """KV shards larger than KV_CHUNK stream through the inner online-
    softmax scan; numerics must match the unchunked reference exactly."""
    import mlcomp_tpu.parallel.ring as ring

    monkeypatch.setattr(ring, "KV_CHUNK", 8)  # S_local=32 -> 4 chunks
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((2, 128, 4, 16), 6)
    k = _rand((2, 128, 2, 16), 7)
    v = _rand((2, 128, 2, 16), 8)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=causal)
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_chunked_kv_grads(monkeypatch):
    import mlcomp_tpu.parallel.ring as ring

    monkeypatch.setattr(ring, "KV_CHUNK", 8)
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 64, 2, 16), 9)
    k = _rand((1, 64, 2, 16), 10)
    v = _rand((1, 64, 2, 16), 11)
    w = _rand((1, 64, 2, 16), 12)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * w)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_chunked_ragged_tail(monkeypatch):
    """KV shard not a chunk multiple: divisible prefix scans, the tail
    merges as one extra tile — the memory bound holds for ragged shards."""
    import mlcomp_tpu.parallel.ring as ring

    monkeypatch.setattr(ring, "KV_CHUNK", 8)
    mesh = make_mesh(MeshSpec(sp=4))
    # S_local = 12 -> one 8-chunk + a 4-tail per ring tile
    q = _rand((1, 48, 2, 16), 13)
    k = _rand((1, 48, 2, 16), 14)
    v = _rand((1, 48, 2, 16), 15)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=True)
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_path_matches_reference(causal):
    """Flash-kernel block compute (interpret mode on CPU): shards of 256
    on a 4-ring == the single-device reference."""
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 1024, 4, 64), 10)
    k = _rand((1, 1024, 2, 64), 11)
    v = _rand((1, 1024, 2, 64), 12)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, causal=causal, use_flash=True
        )
    )(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_ring_flash_path_grads():
    mesh = make_mesh(MeshSpec(sp=4))
    q = _rand((1, 512, 2, 64), 13)
    k = _rand((1, 512, 2, 64), 14)
    v = _rand((1, 512, 2, 64), 15)
    w = _rand((1, 512, 2, 64), 16)

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(
                ring_attention_sharded(
                    q, k, v, mesh, causal=True, use_flash=impl
                ) * w
            )
        return f

    gf = jax.jit(jax.grad(loss(True), argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss(False), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

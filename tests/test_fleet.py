"""Fleet control plane: affinity key stability, router fallback,
autoscaler decision math, manager reconcile/restart/drain, registry
file, readiness split, and the scheduler-launched replica path.

Everything except the two marked integration tests runs with fake
launchers/fetchers and an injected clock — no TPU, no engine, no
sleeping on real health polls."""

import json
import threading
import time
from types import SimpleNamespace

import pytest

from mlcomp_tpu.cache.prefix_key import (
    normalize_ids,
    prefix_hash,
    rendezvous_rank,
)
from mlcomp_tpu.fleet.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    FleetSignals,
)
from mlcomp_tpu.fleet.manager import (
    CallableLauncher,
    ReplicaManager,
    ReplicaSpec,
)
from mlcomp_tpu.fleet.registry import (
    read_registry,
    registry_urls,
    remove_entry,
    update_entry,
)
from mlcomp_tpu.fleet.router import Router


# --------------------------------------------------------- prefix key


def test_prefix_hash_is_process_stable():
    # PINNED digest: affinity keys must survive router restarts and
    # cross-process comparison — a stdlib hash() (seeded per process)
    # or a changed serialization would break this, and with it every
    # replica's warm cache
    assert prefix_hash([1, 2, 3], max_tokens=32) == (
        "abccad42d03c940bc2b249bf5a4e1e3d"
    )
    assert prefix_hash([1, 2, 3]) == prefix_hash((1.0, 2, 3))
    # only the first max_tokens ids matter: a shared system prompt plus
    # different user suffixes share a key
    long_a = list(range(100)) + [7]
    long_b = list(range(100)) + [8]
    assert prefix_hash(long_a, 32) == prefix_hash(long_b, 32)
    assert prefix_hash([1, 2]) != prefix_hash([1, 2, 3])


def test_normalize_ids_matches_trie_walk():
    from mlcomp_tpu.cache.prefix_index import PrefixIndex

    class FakeBlock:
        def __init__(self, n):
            self.ntokens = n
            self.nbytes = n

        def slice(self, a, b):
            return FakeBlock(b - a)

    idx = PrefixIndex(max_bytes=1 << 20)
    idx.insert([5, 6, 7, 8], FakeBlock(4))
    # floats/np-ish inputs coerce exactly like the router's key helper
    lease = idx.lookup((5.0, 6, 7, 8))
    assert lease is not None and lease.tokens == 4
    lease.release()
    assert normalize_ids((5.0, 6)) == (5, 6)


def test_rendezvous_rank_stability_and_minimal_disruption():
    members = [f"fleet-{i}" for i in range(4)]
    keys = [prefix_hash([i, i + 1, i + 2]) for i in range(64)]
    rank_a = {k: rendezvous_rank(k, members) for k in keys}
    # permutation of the member list changes nothing
    rank_b = {k: rendezvous_rank(k, members[::-1]) for k in keys}
    assert rank_a == rank_b
    # removing one member only re-homes the keys it owned
    survivors = members[:-1]
    for k in keys:
        old = rank_a[k][0]
        new = rendezvous_rank(k, survivors)[0]
        if old != members[-1]:
            assert new == old
        else:
            assert new in survivors


# ------------------------------------------------------------- router


def _mk_router(healthz, **kw):
    """Router over fake replicas: ``healthz`` maps url -> dict or
    Exception."""
    def fetch(url, path, timeout=None, payload=None):
        v = healthz[url]
        if isinstance(v, Exception):
            raise v
        return v

    clock = kw.pop("clock", None) or (lambda: 0.0)
    r = Router(urls=list(healthz), fetch=fetch, clock=clock,
               health_poll_s=0.05, **kw)
    r.poll_once()
    return r


def _hz(ok=True, ready=True, depth=0):
    return {"ok": ok, "ready": ready, "queue_depth": depth}


def test_router_affinity_stable_across_restarts():
    urls = [f"http://127.0.0.1:900{i}" for i in range(3)]
    healthz = {u: _hz() for u in urls}
    key = prefix_hash([9, 10, 11, 12])
    picks = set()
    for _ in range(3):  # three fresh "router restarts"
        r = _mk_router(healthz)
        target, reason = r.choose(key)
        assert reason == "affinity"
        picks.add(target["name"])
    assert len(picks) == 1  # same replica every time


def test_router_falls_back_when_affinity_target_429s():
    urls = [f"http://127.0.0.1:901{i}" for i in range(3)]
    healthz = {u: _hz(depth=2) for u in urls}
    now = [0.0]
    r = _mk_router(healthz, clock=lambda: now[0])
    key = prefix_hash([1, 2, 3, 4])
    target, reason = r.choose(key)
    assert reason == "affinity"
    affinity_name = target["name"]
    # make one OTHER replica clearly least-loaded
    light = next(n for n in healthz if n.split("://")[-1] != affinity_name)
    healthz[light] = _hz(depth=0)
    r.poll_once()
    # the affinity target answers 429: the router marks it saturated
    # and the NEXT same-prefix request goes least-loaded
    r.mark_saturated(affinity_name)
    target2, reason2 = r.choose(key)
    assert reason2 == "least_loaded"
    assert target2["name"] != affinity_name
    assert target2["name"] == light.split("://")[-1]
    # the cooldown expires -> affinity returns home
    now[0] += r.saturated_cooldown_s + 0.1
    target3, reason3 = r.choose(key)
    assert (target3["name"], reason3) == (affinity_name, "affinity")


def test_router_routes_around_unhealthy_and_unready():
    urls = [f"http://127.0.0.1:902{i}" for i in range(2)]
    healthz = {u: _hz() for u in urls}
    r = _mk_router(healthz)
    key = prefix_hash([42, 43, 44])
    target, _ = r.choose(key)
    bad = next(u for u in urls if u.endswith(target["name"].split(":")[-1]))
    # ready: false (draining/warming) diverts traffic without a restart
    healthz[bad] = _hz(ready=False)
    r.poll_once()
    t2, reason = r.choose(key)
    assert t2["name"] != target["name"] and reason == "least_loaded"
    # hard-down (connection refused) does too
    healthz[bad] = ConnectionRefusedError("down")
    r.poll_once()
    r.poll_once()
    t3, _ = r.choose(key)
    assert t3["name"] != target["name"]
    # and with EVERY replica down there is nobody to route to
    for u in urls:
        healthz[u] = ConnectionRefusedError("down")
    for _ in range(r.unhealthy_after):
        r.poll_once()
    none, reason = r.choose(key)
    assert none is None and reason == "no_live_replica"


def test_router_saturation_by_queue_depth():
    urls = [f"http://127.0.0.1:903{i}" for i in range(2)]
    healthz = {u: _hz() for u in urls}
    r = _mk_router(healthz, saturation_queue_depth=4)
    key = prefix_hash([7, 8, 9])
    target, _ = r.choose(key)
    deep = next(u for u in urls if u.endswith(target["name"].split(":")[-1]))
    healthz[deep] = _hz(depth=10)  # past the saturation bound
    r.poll_once()
    t2, reason = r.choose(key)
    assert t2["name"] != target["name"] and reason == "least_loaded"


# ---------------------------------------------------------- autoscaler


def _scaler(policy=None, **kw):
    now = [0.0]
    sc = Autoscaler(
        policy or AutoscalePolicy(
            min_replicas=1, max_replicas=4, sustain_s=30.0,
            idle_s=300.0, cooldown_s=60.0,
        ),
        clock=lambda: now[0], **kw,
    )
    return sc, now


BURN = FleetSignals(slo_breached=True, requests_delta=10,
                    live_replicas=2)
REJECTS = FleetSignals(reject_ratio=0.5, requests_delta=10,
                       live_replicas=2)
BUSY = FleetSignals(requests_delta=10, live_replicas=2)
IDLE = FleetSignals(requests_delta=0, live_replicas=2)


def test_autoscaler_table_driven_decisions():
    # (advance_s, signals, expected_direction) — hysteresis pinned
    table = [
        (0, BURN, "hold"),      # breach starts; unsustained
        (10, BURN, "hold"),     # 10s < sustain_s
        (25, BURN, "up"),       # 35s sustained -> scale up
        (10, BURN, "hold"),     # cooldown blocks a second action
        (55, BURN, "up"),       # cooldown over, still burning
        (10, BUSY, "hold"),     # recovered: traffic, no burn
        (100, IDLE, "hold"),    # idle clock starts
        (250, IDLE, "hold"),    # 250s < idle_s
        (100, IDLE, "down"),    # 350s idle -> scale down
        (30, IDLE, "hold"),     # cooldown again
    ]
    sc, now = _scaler()
    results = []
    for dt, sig, want in table:
        now[0] += dt
        d = sc.observe(sig)
        results.append((want, d["direction"], d["reason"]))
    for want, got, reason in results:
        assert want == got, results
    st = sc.stats()
    assert st["actions"] == {"up": 2, "down": 1}


def test_autoscaler_reject_ratio_and_bounds():
    sc, now = _scaler(policy=AutoscalePolicy(
        min_replicas=1, max_replicas=3, sustain_s=0.0, cooldown_s=0.0,
    ))
    d = sc.observe(REJECTS)
    assert d["direction"] == "up" and d["reason"] == "reject_ratio"
    assert d["target"] == 3
    # at the ceiling the decision reports why it held
    at_max = FleetSignals(reject_ratio=0.5, requests_delta=5,
                          live_replicas=3)
    d2 = sc.observe(at_max)
    assert d2["direction"] == "hold" and d2["reason"].endswith("_at_max")
    # and the floor guards the other side
    sc2, now2 = _scaler(policy=AutoscalePolicy(
        min_replicas=1, max_replicas=4, idle_s=0.0, cooldown_s=0.0,
    ))
    d3 = sc2.observe(FleetSignals(live_replicas=1))
    assert d3["direction"] == "hold" and d3["reason"].endswith("_at_min")


def test_autoscaler_dry_run_logs_but_does_not_apply():
    calls = []
    mgr = SimpleNamespace(
        target=2, set_target=lambda n: calls.append(n), urls=lambda: [],
    )
    sc, now = _scaler(policy=AutoscalePolicy(
        min_replicas=1, max_replicas=4, sustain_s=0.0, cooldown_s=0.0,
    ), manager=mgr, dry_run=True)
    d = sc.observe(BURN)
    assert d["direction"] == "up" and d["dry_run"] and not d["applied"]
    assert calls == []  # decision logged, lever untouched
    assert sc.decisions[-1]["reason"] == "slo_burn"
    # live mode applies through the manager
    sc2, _ = _scaler(policy=AutoscalePolicy(
        min_replicas=1, max_replicas=4, sustain_s=0.0, cooldown_s=0.0,
    ), manager=mgr, dry_run=False)
    d2 = sc2.observe(BURN)
    assert d2["applied"] and calls == [3]


def test_autoscaler_scrape_builds_signals_from_healthz():
    payloads = {
        "http://a": {
            "ok": True, "requests": 100, "rejected": {"queue_full": 10},
            "slo": {"breached": [], "burn_rate": {
                "ttft_p95": {"fast": 2.0, "slow": 1.5},
            }},
        },
        "http://b": ConnectionRefusedError("down"),
    }

    def fetch(url, path, timeout=None, payload=None):
        v = payloads[url]
        if isinstance(v, Exception):
            raise v
        return v

    sc = Autoscaler(AutoscalePolicy(), fetch=fetch)
    s1 = sc.scrape(["http://a", "http://b"])
    # both windows burn above threshold -> overload even without the
    # SLO engine's own breached list
    assert s1.slo_breached and s1.live_replicas == 1
    assert s1.detail["http://b"] == "unreachable"
    # second scrape differences the counters
    payloads["http://a"]["requests"] = 140
    payloads["http://a"]["rejected"] = {"queue_full": 30}
    s2 = sc.scrape(["http://a", "http://b"])
    assert s2.requests_delta == 40
    assert s2.reject_ratio == pytest.approx(20 / 60)


# ----------------------------------------------------------- registry


def test_registry_file_merge_and_urls(tmp_path):
    path = str(tmp_path / "reg.json")
    assert read_registry(path) == {}
    update_entry(path, "fleet-0", url="http://h:1", state="starting")
    # a writer that doesn't know the url must not erase it
    update_entry(path, "fleet-0", url=None, state="live")
    update_entry(path, "fleet-1", url="http://h:2", state="live")
    data = read_registry(path)
    assert data["fleet-0"]["url"] == "http://h:1"
    assert data["fleet-0"]["state"] == "live"
    assert registry_urls(path) == ["http://h:1", "http://h:2"]
    assert registry_urls(path, states=["live"]) == [
        "http://h:1", "http://h:2",
    ]
    remove_entry(path, "fleet-0")
    assert registry_urls(path) == ["http://h:2"]
    # garbled file reads as empty, never raises
    with open(path, "w") as f:
        f.write("{not json")
    assert read_registry(path) == {}


def test_report_server_fleet_urls_prefer_registry(tmp_path,
                                                  monkeypatch):
    from mlcomp_tpu.report.server import _fleet_urls

    path = str(tmp_path / "reg.json")
    update_entry(path, "r0", url="http://dyn:1", state="live")
    monkeypatch.setenv("MLCOMP_TPU_SERVE_REGISTRY", path)
    monkeypatch.setenv("MLCOMP_TPU_SERVE_URLS", "http://static:9")
    assert _fleet_urls() == ["http://dyn:1"]
    # an empty registry falls back to the static env wiring
    remove_entry(path, "r0")
    assert _fleet_urls() == ["http://static:9"]


# ------------------------------------------------------------ manager


class _FakeFleet:
    """A launcher + fetch pair simulating replicas without HTTP."""

    def __init__(self):
        self.spawned = []
        self.stopped = []
        self.health = {}   # name -> healthz dict or Exception

    def launcher(self):
        def spawn(name, port):
            self.spawned.append(name)
            self.health.setdefault(
                name, {"ok": True, "ready": True, "queue_depth": 0}
            )
            return SimpleNamespace(
                url=f"http://fake/{name}",
                stop=lambda n=name: self.stopped.append(n),
            )

        return CallableLauncher(spawn)

    def fetch(self, url, path, timeout=None, payload=None):
        name = url.rsplit("/", 1)[-1]
        if path == "/drain":
            self.health[name]["ready"] = False
            return {"ok": True, "draining": True}
        v = self.health[name]
        if isinstance(v, Exception):
            raise v
        return v


def _mk_manager(fleet, tmp_path, now, **spec_kw):
    spec_kw.setdefault("target", 2)
    spec_kw.setdefault("unhealthy_after", 2)
    spec_kw.setdefault("restart_budget", 2)
    spec_kw.setdefault("healthy_reset_s", 50.0)
    # no startup grace: these tables drive the fake clock by hand and
    # the fake replicas are "bound" the instant they spawn
    spec_kw.setdefault("startup_grace_s", 0.0)
    return ReplicaManager(
        fleet.launcher(), ReplicaSpec(**spec_kw),
        registry_path=str(tmp_path / "reg.json"),
        clock=lambda: now[0], fetch=fleet.fetch,
    )


def test_manager_reconciles_to_target_and_registers(tmp_path):
    fleet = _FakeFleet()
    now = [0.0]
    mgr = _mk_manager(fleet, tmp_path, now)
    mgr.tick()
    assert fleet.spawned == ["fleet-0", "fleet-1"]
    st = mgr.stats()
    assert st["live"] == 2 and st["target"] == 2
    reg = read_registry(str(tmp_path / "reg.json"))
    assert sorted(reg) == ["fleet-0", "fleet-1"]
    assert all(e["state"] == "live" for e in reg.values())
    # scale up through the autoscaler's lever
    mgr.set_target(3)
    mgr.tick()
    assert fleet.spawned[-1] == "fleet-2"
    assert mgr.stats()["live"] == 3


def test_manager_restarts_unhealthy_with_bounded_budget(tmp_path):
    fleet = _FakeFleet()
    now = [0.0]
    mgr = _mk_manager(fleet, tmp_path, now, target=1)
    mgr.tick()
    assert fleet.spawned == ["fleet-0"]
    # watchdog 503s: ok false but answering — same restart path
    fleet.health["fleet-0"] = {"ok": False, "ready": False,
                               "queue_depth": 0}

    def fail_polls(n):
        for _ in range(n):
            now[0] += 1.0
            mgr.tick()

    fail_polls(2)  # unhealthy_after=2 -> restart #1
    assert fleet.spawned.count("fleet-0") == 2
    assert fleet.stopped.count("fleet-0") == 1
    fail_polls(2)  # restart #2 — budget exhausted after this
    assert fleet.spawned.count("fleet-0") == 3
    fail_polls(4)  # budget spent: no more spawns, state=failed
    assert fleet.spawned.count("fleet-0") == 3
    st = mgr.stats()
    assert st["states"].get("failed") == 1
    assert st["restarts"]["unhealthy"] == 2
    assert st["restarts"]["budget_exhausted"] == 1
    # a budget-exhausted replica HOLDS its slot: no replacement
    # cascade spawning fleet-1, fleet-2, ... through fresh budgets
    assert fleet.spawned == ["fleet-0"] * 3


def test_manager_startup_grace_tolerates_slow_boot(tmp_path):
    """The bug the end-to-end CLI drive caught: a real serve child
    takes tens of seconds to load weights before it binds, and without
    startup grace the manager kill-looped every booting replica
    through its whole restart budget, then cascaded replacements until
    the port range exhausted."""
    fleet = _FakeFleet()
    now = [0.0]
    # pre-set: the replica will NOT answer its health port yet
    fleet.health["fleet-0"] = ConnectionRefusedError("still booting")
    mgr = _mk_manager(fleet, tmp_path, now, target=1,
                      startup_grace_s=30.0)
    for _ in range(10):
        now[0] += 1.0
        mgr.tick()
    # ten silent polls inside the grace: no restart, no kill-loop
    assert fleet.spawned == ["fleet-0"]
    assert fleet.stopped == []
    # grace expires with still no answer -> the normal restart
    # machinery engages
    now[0] = 40.0
    mgr.tick()
    now[0] += 1.0
    mgr.tick()
    assert fleet.spawned.count("fleet-0") == 2
    # ... and the fresh incarnation finally boots healthy
    fleet.health["fleet-0"] = {"ok": True, "ready": True,
                               "queue_depth": 0}
    now[0] += 1.0
    mgr.tick()
    assert mgr.stats()["live"] == 1
    # a replica that HAS been healthy gets no grace on its next death
    fleet.health["fleet-0"] = ConnectionRefusedError("crashed")
    now[0] += 1.0
    mgr.tick()
    now[0] += 1.0
    mgr.tick()
    assert fleet.spawned.count("fleet-0") == 3  # detected at the bound


def test_manager_progress_gate_refills_restart_budget(tmp_path):
    fleet = _FakeFleet()
    now = [0.0]
    mgr = _mk_manager(fleet, tmp_path, now, target=1)
    mgr.tick()
    fleet.health["fleet-0"] = ConnectionRefusedError("down")
    for _ in range(2):
        now[0] += 1.0
        mgr.tick()
    assert fleet.spawned.count("fleet-0") == 2  # one restart spent
    # the restarted replica HOLDS healthy past healthy_reset_s
    fleet.health["fleet-0"] = {"ok": True, "ready": True,
                               "queue_depth": 0}
    now[0] += 60.0
    mgr.tick()
    with mgr._lock:
        assert mgr._replicas["fleet-0"].restarts == 0  # refilled


def test_manager_drains_before_scale_down(tmp_path):
    fleet = _FakeFleet()
    now = [0.0]
    mgr = _mk_manager(fleet, tmp_path, now, target=2,
                      drain_timeout_s=100.0)
    mgr.tick()
    fleet.health["fleet-1"]["queue_depth"] = 3  # in-flight work
    now[0] += 1.0
    mgr.tick()
    mgr.set_target(1)
    now[0] += 1.0
    mgr.tick()
    # drained, not killed: the replica got POST /drain (ready False)
    # and is still running while its queue empties
    assert fleet.health["fleet-1"]["ready"] is False
    assert "fleet-1" not in fleet.stopped
    reg = read_registry(str(tmp_path / "reg.json"))
    assert reg["fleet-1"]["state"] == "draining"
    # the queue empties but a stream is still DECODING in a slot
    # (queue_depth never counts active slots): the stop must wait
    fleet.health["fleet-1"]["queue_depth"] = 0
    fleet.health["fleet-1"]["engine"] = {"active_slots": 1}
    now[0] += 1.0
    mgr.tick()
    now[0] += 1.0
    mgr.tick()
    assert "fleet-1" not in fleet.stopped
    # the stream finishes -> the stop lands and the registry entry goes
    fleet.health["fleet-1"]["engine"] = {"active_slots": 0}
    now[0] += 1.0
    mgr.tick()
    now[0] += 1.0
    mgr.tick()
    assert "fleet-1" in fleet.stopped
    assert "fleet-1" not in read_registry(str(tmp_path / "reg.json"))
    # and no replacement was spawned for it
    assert fleet.spawned == ["fleet-0", "fleet-1"]


def test_manager_metrics_families(tmp_path):
    from mlcomp_tpu.obs.metrics import Registry

    fleet = _FakeFleet()
    now = [0.0]
    reg = Registry()
    mgr = ReplicaManager(
        fleet.launcher(), ReplicaSpec(target=1),
        metrics=reg, registry_path=str(tmp_path / "reg.json"),
        clock=lambda: now[0], fetch=fleet.fetch,
    )
    mgr.tick()
    text = reg.render()
    assert "mlcomp_fleet_replicas_target 1" in text
    assert "mlcomp_fleet_replicas_live 1" in text
    assert 'mlcomp_fleet_replica_restarts_total{reason="unhealthy"} 0' \
        in text


# ---------------------------------------------- serve readiness + drain


@pytest.fixture(scope="module")
def toy_service():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.serve import GenerationService
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1,),
        prompt_buckets=(16,), max_new_buckets=(8,),
        metrics_history_interval=0,
    )
    yield svc
    svc.close()


def test_ready_splits_from_ok(toy_service):
    st = toy_service.stats()
    assert st["healthy"] and st["ready"] and not st["draining"]
    toy_service.set_draining(True)
    st = toy_service.stats()
    # draining: NOT ready (router diverts) but still ok (manager must
    # not restart a deliberately draining daemon)
    assert st["healthy"] and not st["ready"] and st["draining"]
    toy_service.set_draining(False)
    assert toy_service.stats()["ready"]


def test_drain_route_flips_readiness(toy_service):
    import urllib.request

    from mlcomp_tpu.serve import make_http_server

    httpd = make_http_server(toy_service, "127.0.0.1", 0, "toy")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def post_drain(draining):
        req = urllib.request.Request(
            f"{base}/drain",
            data=json.dumps({"draining": draining}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    try:
        assert post_drain(True) == {"ok": True, "draining": True}
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            hz = json.loads(r.read())
            assert r.status == 200  # draining is NOT unhealthy
        assert hz["ok"] and not hz["ready"] and hz["draining"]
        assert post_drain(False)["draining"] is False
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            assert json.loads(r.read())["ready"]
    finally:
        httpd.shutdown()
        httpd.server_close()
        toy_service.set_draining(False)


# ------------------------------------------- scheduler-launched replica


def test_scheduler_launcher_runs_replica_as_task(tmp_path):
    """The tentpole's scheduler leg: a replica submitted as a
    single-task DAG, claimed by a Worker, serving until stopped —
    URL published to and removed from the registry by the executor."""
    import urllib.request

    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.fleet.manager import SchedulerLauncher
    from mlcomp_tpu.scheduler.supervisor import Supervisor
    from mlcomp_tpu.scheduler.worker import Worker

    db = str(tmp_path / "store.sqlite")
    registry_path = str(tmp_path / "reg.json")
    store = Store(db)
    launcher = SchedulerLauncher(
        store,
        model_cfg={
            "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
            "layers": 1, "heads": 2, "mlp_dim": 64,
            "dtype": "float32",
        },
        registry_path=registry_path,
        serve_args={
            "batch_sizes": [1], "prompt_buckets": [16],
            "max_new_buckets": [8], "metrics_history_interval": 0,
            "stop_poll_s": 0.2,
        },
    )
    handle = launcher.spawn("fleet-0", 0)
    assert handle.url is None  # not published yet
    Supervisor(store).tick()  # queue the replica task

    def run_worker():
        # the Worker's Store must be created on the thread that uses
        # it (sqlite connections are per-thread)
        w = Worker(
            Store(db), name="w0", workdir=str(tmp_path / "w"),
            isolate=False,
        )
        w.run_once()

    t = threading.Thread(target=run_worker, daemon=True)
    t.start()
    try:
        deadline = time.time() + 120
        url = None
        while time.time() < deadline:
            url = handle.url
            if url:
                break
            time.sleep(0.1)
        assert url, "replica never published its URL"
        assert read_registry(registry_path)["fleet-0"]["url"] == url
        with urllib.request.urlopen(f"{url}/healthz", timeout=30) as r:
            hz = json.loads(r.read())
        assert hz["ok"] and hz["model"] == "transformer_lm"
        # the manager's stop: flip the task row; the executor's
        # ownership poll tears the daemon down and deregisters
        handle.stop()
        t.join(timeout=60)
        assert not t.is_alive(), "worker did not release the replica"
        assert "fleet-0" not in read_registry(registry_path)
    finally:
        if t.is_alive():
            store.stop_dag(handle.dag_id)
            t.join(timeout=60)
        store.close()

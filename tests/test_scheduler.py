import json
import time

from mlcomp_tpu.dag.parser import parse_dag
from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.local import run_dag_local
from mlcomp_tpu.scheduler.supervisor import Supervisor
from mlcomp_tpu.scheduler.worker import Worker


def test_linear_dag_end_to_end(tmp_db):
    statuses = run_dag_local(
        """
info: {name: lin}
executors:
  a: {type: noop}
  b: {type: noop, depends: a}
  c: {type: noop, depends: b}
""",
        db_path=tmp_db,
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values())


def test_failure_skips_downstream(tmp_db):
    statuses = run_dag_local(
        """
info: {name: f}
executors:
  a: {type: noop}
  boom: {type: fail, depends: a}
  after: {type: noop, depends: boom}
  side: {type: noop, depends: a}
""",
        db_path=tmp_db,
    )
    assert statuses["a"] == TaskStatus.SUCCESS
    assert statuses["boom"] == TaskStatus.FAILED
    assert statuses["after"] == TaskStatus.SKIPPED
    assert statuses["side"] == TaskStatus.SUCCESS


def test_retry_then_success(tmp_db, tmp_path):
    # a pyfunc that fails once then succeeds, via a file-based counter
    marker = tmp_path / "attempts.txt"
    statuses = run_dag_local(
        {
            "info": {"name": "retry"},
            "executors": {
                "flaky": {
                    "type": "pyfunc",
                    "max_retries": 2,
                    "args": {
                        "target": "tests.helpers_flaky:fail_once",
                        "kwargs": {"marker": str(marker)},
                    },
                }
            },
        },
        db_path=tmp_db,
    )
    assert statuses["flaky"] == TaskStatus.SUCCESS
    assert marker.read_text() == "11"  # two attempts recorded


def test_grid_fanout_parallel_workers(tmp_db):
    statuses = run_dag_local(
        """
info: {name: grid}
executors:
  train:
    type: noop
    grid: {lr: [1, 2, 3, 4]}
  join: {type: noop, depends: train}
""",
        workers=4,
        db_path=tmp_db,
    )
    assert len(statuses) == 5
    assert all(s == TaskStatus.SUCCESS for s in statuses.values())


def test_dead_worker_requeue(tmp_db):
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        parse_dag(
            "info: {name: dw}\nexecutors:\n  a: {type: noop, max_retries: 1}"
        )
    )
    sup = Supervisor(store, worker_timeout_s=0.01)
    sup.tick()  # queues 'a'
    # worker claims then "dies" (no more heartbeats, task left in_progress)
    dead = Store(tmp_db)
    dead.heartbeat("zombie", chips=0)
    claim = dead.claim_task("zombie", free_chips=0)
    assert claim is not None
    time.sleep(0.05)
    sup.tick()  # reaps zombie, requeues task
    assert store.task_statuses(dag_id)["a"] == TaskStatus.QUEUED
    # a healthy worker finishes it
    w = Worker(Store(tmp_db), name="healthy", chips=0)
    assert w.run_once() is True
    sup.tick()
    assert store.dag_status(dag_id) == "success"


def test_shell_and_submit_executors(tmp_db, tmp_path):
    art = tmp_path / "model.bin"
    statuses = run_dag_local(
        {
            "info": {"name": "pkg"},
            "executors": {
                "make": {
                    "type": "shell",
                    "args": {"command": f"echo weights > {art}"},
                },
                "pack": {
                    "type": "submit",
                    "depends": "make",
                    "args": {
                        "sources": [str(art)],
                        "out": str(tmp_path / "sub.tar.gz"),
                    },
                },
            },
        },
        db_path=tmp_db,
        workdir=str(tmp_path),
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values())
    assert (tmp_path / "sub.tar.gz").exists()

"""DAG lifecycle: stop, restart, status — store, CLI, and HTTP surfaces."""

import json
import urllib.request

import pytest

from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.supervisor import Supervisor
from mlcomp_tpu.scheduler.worker import Worker


def _chain(store, n=3, fail_at=None):
    tasks = []
    for i in range(n):
        ex = "fail" if i == fail_at else "noop"
        deps = (f"t{i-1}",) if i else ()
        tasks.append(TaskSpec(name=f"t{i}", executor=ex, depends=deps))
    return store.submit_dag(DagSpec(name="d", project="p", tasks=tuple(tasks)))


def test_stop_dag_halts_everything(tmp_db):
    store = Store(tmp_db)
    dag_id = _chain(store)
    sup = Supervisor(store, worker_timeout_s=30)
    sup.tick()  # t0 queued
    n = store.stop_dag(dag_id)
    assert n == 3
    assert store.dag_status(dag_id) == "stopped"
    assert all(
        s == TaskStatus.STOPPED for s in store.task_statuses(dag_id).values()
    )
    # stopped DAG is not advanced further
    assert sup.tick()[dag_id] == "stopped"
    store.close()


def test_restart_after_failure_reruns_only_unsuccessful(tmp_db):
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store, n=3, fail_at=1)
    sup = Supervisor(store, worker_timeout_s=30)
    w = Worker(store, name="w", chips=0, load_jax_executors=False)
    for _ in range(6):
        status = sup.tick()[dag_id]
        if status != "in_progress":
            break
        while w.run_once():
            pass
    assert status == "failed"
    sts = store.task_statuses(dag_id)
    assert sts["t0"] == TaskStatus.SUCCESS
    assert sts["t1"] == TaskStatus.FAILED
    assert sts["t2"] == TaskStatus.SKIPPED

    # flip the failing executor to noop by rewriting args? simpler: restart
    # and verify t1 re-fails but t0 is not re-run (its result is kept)
    n = store.restart_dag(dag_id)
    assert n == 2  # t1 + t2 reset; t0 kept
    assert store.dag_status(dag_id) == "in_progress"
    sts = store.task_statuses(dag_id)
    assert sts["t0"] == TaskStatus.SUCCESS
    assert sts["t1"] == TaskStatus.NOT_RAN
    store.close()


def test_restart_stopped_dag_completes(tmp_db):
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store)
    sup = Supervisor(store, worker_timeout_s=30)
    sup.tick()
    store.stop_dag(dag_id)
    assert store.restart_dag(dag_id) == 3
    w = Worker(store, name="w", chips=0, load_jax_executors=False)
    for _ in range(6):
        status = sup.tick()[dag_id]
        if status != "in_progress":
            break
        while w.run_once():
            pass
    assert status == "success"
    store.close()


def test_stale_worker_cannot_clobber_stop(tmp_db):
    """finish_task(expect_worker) after a stop must be a no-op."""
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store, n=1)
    Supervisor(store, worker_timeout_s=30).tick()
    claim = store.claim_task("w0", free_chips=0, free_hosts=1)
    assert claim is not None
    store.stop_dag(dag_id)
    ok = store.finish_task(
        claim["id"], TaskStatus.SUCCESS, result={}, expect_worker="w0"
    )
    assert not ok
    assert store.task_statuses(dag_id)["t0"] == TaskStatus.STOPPED
    store.close()


def test_cli_status_stop_restart(tmp_db, capsys):
    from mlcomp_tpu.cli import main

    store = Store(tmp_db)
    dag_id = _chain(store)
    store.close()
    assert main(["status", "--db", tmp_db]) == 0
    out = capsys.readouterr().out
    assert "in_progress" in out
    assert main(["stop", str(dag_id), "--db", tmp_db]) == 0
    assert json.loads(capsys.readouterr().out)["stopped_tasks"] == 3
    assert main(["restart", str(dag_id), "--db", tmp_db]) == 0
    assert json.loads(capsys.readouterr().out)["reset_tasks"] == 3
    assert main(["status", str(dag_id), "--db", tmp_db]) == 0
    assert "not_ran" in capsys.readouterr().out


def test_http_stop_restart(tmp_db):
    from mlcomp_tpu.report.server import start_in_thread

    store = Store(tmp_db)
    dag_id = _chain(store)
    srv, port = start_in_thread(tmp_db)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/dags/{dag_id}/stop", method="POST",
            headers={"X-Requested-With": "mlcomp-tpu"},
        )
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["stopped_tasks"] == 3
        assert store.dag_status(dag_id) == "stopped"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/dags/{dag_id}/restart", method="POST",
            headers={"X-Requested-With": "mlcomp-tpu"},
        )
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["reset_tasks"] == 3
        assert store.dag_status(dag_id) == "in_progress"
    finally:
        srv.shutdown()
        store.close()


def test_post_without_csrf_header_rejected(tmp_db):
    import urllib.error

    from mlcomp_tpu.report.server import start_in_thread

    store = Store(tmp_db)
    dag_id = _chain(store)
    srv, port = start_in_thread(tmp_db)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/dags/{dag_id}/stop", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 403
        assert store.dag_status(dag_id) == "in_progress"  # untouched
    finally:
        srv.shutdown()
        store.close()


def test_stale_worker_failure_cannot_resurrect_stopped_task(tmp_db):
    """A worker whose task was stopped mid-run must not requeue it on
    failure (regression: requeue_task was unconditional)."""
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store, n=1)
    Supervisor(store, worker_timeout_s=30).tick()
    claim = store.claim_task("w0", free_chips=0, free_hosts=1)
    store.stop_dag(dag_id)
    # stale worker reports failure after the stop: both requeue and fail
    # must be no-ops
    assert not store.requeue_task(claim["id"], expect_worker="w0")
    assert not store.finish_task(
        claim["id"], TaskStatus.FAILED, error="x", expect_worker="w0"
    )
    assert store.task_statuses(dag_id)["t0"] == TaskStatus.STOPPED
    store.close()


def test_restart_reopens_stopped_dag_with_all_tasks_succeeded(tmp_db):
    """stop after full success must not brick the DAG (regression:
    restart_dag skipped the dag-status flip when no tasks reset)."""
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store, n=1)
    sup = Supervisor(store, worker_timeout_s=30)
    w = Worker(store, name="w", chips=0, load_jax_executors=False)
    sup.tick()
    w.run_once()
    assert store.task_statuses(dag_id)["t0"] == TaskStatus.SUCCESS
    # stop lands between success and the finalize tick
    store.stop_dag(dag_id)
    assert store.dag_status(dag_id) == "stopped"
    assert store.restart_dag(dag_id) == 0  # nothing to reset...
    assert store.dag_status(dag_id) == "in_progress"  # ...but reopened
    assert sup.tick()[dag_id] == "success"
    store.close()


def _task_ids(store, dag_id):
    return {r["name"]: r["id"] for r in store.task_rows(dag_id)}


def test_stop_single_task_dooms_dependents(tmp_db):
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store)
    sup = Supervisor(store, worker_timeout_s=30)
    sup.tick()
    ids = _task_ids(store, dag_id)
    assert store.stop_task(ids["t1"])
    w = Worker(store, name="w", chips=0, load_jax_executors=False)
    for _ in range(6):
        status = sup.tick()[dag_id]
        if status != "in_progress":
            break
        while w.run_once():
            pass
    sts = store.task_statuses(dag_id)
    assert sts["t0"] == TaskStatus.SUCCESS  # untouched branch still ran
    assert sts["t1"] == TaskStatus.STOPPED
    assert sts["t2"] == TaskStatus.SKIPPED  # doomed by the stopped parent
    assert status == "failed"
    store.close()


def test_restart_task_resets_skipped_dependents(tmp_db):
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store, n=3, fail_at=1)
    sup = Supervisor(store, worker_timeout_s=30)
    w = Worker(store, name="w", chips=0, load_jax_executors=False)
    for _ in range(6):
        if sup.tick()[dag_id] != "in_progress":
            break
        while w.run_once():
            pass
    ids = _task_ids(store, dag_id)
    n = store.restart_task(ids["t1"])
    assert n == 2  # t1 + its skipped dependent t2; successful t0 kept
    sts = store.task_statuses(dag_id)
    assert sts["t0"] == TaskStatus.SUCCESS
    assert sts["t1"] == TaskStatus.NOT_RAN
    assert sts["t2"] == TaskStatus.NOT_RAN
    assert store.dag_status(dag_id) == "in_progress"
    store.close()


def test_restart_task_rejects_unfinished(tmp_db):
    store = Store(tmp_db)
    dag_id = _chain(store)
    ids = _task_ids(store, dag_id)
    assert store.restart_task(ids["t0"]) == 0  # not_ran: nothing to redo
    assert store.stop_task(ids["t0"])
    assert not store.stop_task(ids["t0"])  # already stopped: no-op
    assert store.restart_task(ids["t0"]) == 1
    store.close()


def test_cli_per_task_stop_restart(tmp_db, capsys):
    from mlcomp_tpu.cli import main

    store = Store(tmp_db)
    dag_id = _chain(store)
    ids = _task_ids(store, dag_id)
    store.close()
    assert main(["stop", "--task", str(ids["t1"]), "--db", tmp_db]) == 0
    assert json.loads(capsys.readouterr().out)["stopped"] is True
    assert main(["restart", "--task", str(ids["t1"]), "--db", tmp_db]) == 0
    assert json.loads(capsys.readouterr().out)["reset_tasks"] == 1
    # exactly one of dag / --task must be given
    assert main(["stop", "--db", tmp_db]) == 2
    assert main(["stop", str(dag_id), "--task", "1", "--db", tmp_db]) == 2


def test_http_per_task_stop_restart(tmp_db):
    from mlcomp_tpu.report.server import start_in_thread

    store = Store(tmp_db)
    dag_id = _chain(store)
    ids = _task_ids(store, dag_id)
    srv, port = start_in_thread(tmp_db)
    try:
        def post(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", method="POST",
                headers={"X-Requested-With": "mlcomp-tpu"},
            )
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        assert post(f"/api/tasks/{ids['t2']}/stop")["stopped"] is True
        assert store.task_statuses(dag_id)["t2"] == TaskStatus.STOPPED
        assert post(f"/api/tasks/{ids['t2']}/restart")["reset_tasks"] == 1
        assert store.task_statuses(dag_id)["t2"] == TaskStatus.NOT_RAN
    finally:
        srv.shutdown()
        store.close()


def test_restart_task_pulls_back_queued_dependents(tmp_db):
    """Restarting a succeeded task must de-queue dependents so they cannot
    run against the upstream output while it is being rewritten."""
    from mlcomp_tpu.executors import load_all

    load_all()
    store = Store(tmp_db)
    dag_id = _chain(store)
    sup = Supervisor(store, worker_timeout_s=30)
    w = Worker(store, name="w", chips=0, load_jax_executors=False)
    sup.tick()            # t0 queued
    while w.run_once():   # t0 success
        pass
    sup.tick()            # t1 queued
    ids = _task_ids(store, dag_id)
    assert store.task_statuses(dag_id)["t1"] == TaskStatus.QUEUED
    n = store.restart_task(ids["t0"])
    assert n == 2  # t0 + queued dependent t1
    sts = store.task_statuses(dag_id)
    assert sts["t0"] == TaskStatus.NOT_RAN
    assert sts["t1"] == TaskStatus.NOT_RAN
    # a worker cannot claim anything until the supervisor re-queues t0
    assert store.claim_task("w", free_chips=0, free_hosts=1) is None
    store.close()

"""Test harness: force an 8-device virtual CPU mesh before JAX loads.

Real multi-chip hardware is unavailable in CI; sharding/collective tests run
on XLA's host-platform virtual devices instead (same SPMD partitioner, same
collective lowering).
"""

import os

# Must be set before the first `import jax` anywhere in the test process.
# Hard override (not setdefault): the ambient environment pins
# JAX_PLATFORMS to the real TPU backend, whose init can take ~minutes and
# which tests must never depend on.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# The TPU-VM image's sitecustomize registers the axon TPU plugin in EVERY
# python process whose env carries this trigger — including the worker
# child processes tests spawn (scheduler/child.py), where a half-registered
# TPU backend breaks CPU jax.distributed.  Tests are CPU-only by contract;
# strip the trigger so children start clean.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import pytest  # noqa: E402

# The TPU-VM image's sitecustomize force-registers the axon TPU plugin and
# sets jax_platforms="axon,cpu" *in-process*, overriding the env var — so any
# backend query would first try to init the TPU tunnel (slow, can stall).
# Re-pin the config to cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture()
def tmp_db(tmp_path):
    return str(tmp_path / "mlcomp.sqlite")


# compiled-program pool per engine config (the _fns idiom from
# tests/test_engine_fused_admit.py), shared by the engine test files:
# pipeline depth is HOST-side only, so e.g. the depth-1 and depth-2
# arms of an equality pair share the same jitted
# dispatch/prefill/insert programs — compile once per key, not once
# per engine.  Keys are per-file tuples; files must not collide.
ENGINE_FNS_POOL: dict = {}


def share_engine_fns(eng, key):
    pool = ENGINE_FNS_POOL.setdefault(key, {})
    eng._fns.update(pool)
    eng._fns_pool = pool
    return eng


def close_pooled_engine(eng):
    """Harvest the engine's compiled programs back into its pool,
    then close — the update must precede close() so programs compiled
    by THIS engine survive for the next one."""
    if hasattr(eng, "_fns_pool"):
        eng._fns_pool.update(eng._fns)
    eng.close()


@pytest.fixture(autouse=True)
def _clear_process_mesh():
    """The installed mesh is a process-wide global (production installs
    it once per Trainer/service lifetime); tests that install one and
    don't clean up would silently flip OTHER tests onto mesh-gated
    paths (sharded kernel islands, fold_norms disabled, the chunk
    kernel's XLA fallback) — the round-5 full-suite run caught exactly
    that. Every test starts and ends mesh-free."""
    yield
    from mlcomp_tpu.parallel.mesh import set_current_mesh

    set_current_mesh(None)

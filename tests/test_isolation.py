"""Per-task subprocess isolation: crash containment, pinning, stop-kill.

These tests spawn real child processes (scheduler/child.py), so each task
pays a fresh-interpreter JAX import (~seconds on CPU) — kept to a handful
of tasks for suite-time sanity.
"""

import json
import os
import time

import pytest

from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, ResourceSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.worker import Worker


def _submit(store, *tasks):
    dag = DagSpec(name="iso", project="t", tasks=tuple(tasks))
    dag_id = store.submit_dag(dag)
    names = [t.name for t in tasks]
    store.set_task_status(dag_id, names, TaskStatus.QUEUED)
    return dag_id


def _row(store, dag_id, name):
    return {r["name"]: r for r in store.task_rows(dag_id)}[name]


@pytest.fixture()
def store(tmp_db):
    s = Store(tmp_db)
    yield s
    s.close()


def test_child_process_isolation_and_result_roundtrip(store, tmp_path):
    """The task really runs in another process and its result comes back."""
    dag_id = _submit(
        store,
        TaskSpec(
            name="pid",
            executor="shell",
            args={"command": "echo pid $$"},
        ),
    )
    w = Worker(store, name="iso-w", chips=0, workdir=str(tmp_path),
               isolate=True, load_jax_executors=False)
    assert w.run_once() is True
    row = _row(store, dag_id, "pid")
    assert row["status"] == TaskStatus.SUCCESS.value
    assert json.loads(row["result"]) == {"returncode": 0}
    logs = " ".join(l["message"] for l in store.task_logs(row["id"]))
    assert "spawned child pid" in logs


def test_hard_child_death_survives_and_worker_claims_next(store, tmp_path):
    """VERDICT r1 'done' criterion: a kill-flavor fault inside an executor
    no longer kills the worker loop; the worker claims the next task."""
    dag_id = _submit(
        store,
        TaskSpec(name="victim", executor="noop", args={}),
        TaskSpec(name="next", executor="noop", args={}),
    )
    w = Worker(
        store, name="iso-w", chips=0, workdir=str(tmp_path), isolate=True,
        load_jax_executors=False,
        # armed in the CHILD's env only: os._exit(137) mid-run_task
        child_env={"MLCOMP_FAULTS": "executor.work:kill:1"},
    )
    assert w.run_once() is True   # victim: child dies hard; worker survives
    victim = _row(store, dag_id, "victim")
    assert victim["status"] == TaskStatus.FAILED.value  # max_retries=0
    assert "died" in (victim["error"] or "")
    w.child_env = {}              # env faults re-arm per fresh child process
    assert w.run_once() is True   # the loop lives on and claims 'next'
    after = _row(store, dag_id, "next")
    assert after["status"] == TaskStatus.SUCCESS.value


def test_hard_death_consumes_retry_then_succeeds(store, tmp_path):
    dag_id = _submit(
        store,
        TaskSpec(name="flaky", executor="noop", args={}, max_retries=1),
    )
    w = Worker(
        store, name="iso-w", chips=0, workdir=str(tmp_path), isolate=True,
        load_jax_executors=False,
        child_env={"MLCOMP_FAULTS": "executor.work:kill:1"},
    )
    assert w.run_once() is True   # dies; requeued (1 retry)
    assert _row(store, dag_id, "flaky")["status"] == TaskStatus.QUEUED.value
    w.child_env = {}              # env faults re-arm per fresh child process
    assert w.run_once() is True   # retry attempt succeeds
    assert _row(store, dag_id, "flaky")["status"] == TaskStatus.SUCCESS.value


def test_chip_pinning_env(store, tmp_path):
    """A task taking a strict subset of the worker's chips sees only its
    chip ids in TPU_VISIBLE_DEVICES; MLCOMP_TPU_CHIP_IDS is always set."""
    out = tmp_path / "env.txt"
    dag_id = _submit(
        store,
        TaskSpec(
            name="pin",
            executor="shell",
            args={
                "command":
                f"echo \"ids=$MLCOMP_TPU_CHIP_IDS vis=$TPU_VISIBLE_DEVICES\""
                f" > {out}"
            },
            resources=ResourceSpec(chips=2),
        ),
    )
    w = Worker(store, name="iso-w", chips=4, workdir=str(tmp_path),
               isolate=True, load_jax_executors=False)
    assert w.run_once() is True
    assert _row(store, dag_id, "pin")["status"] == TaskStatus.SUCCESS.value
    assert out.read_text().strip() == "ids=0,1 vis=0,1"


def test_stop_kills_running_child(store, tmp_path):
    """Stopping an in-progress task terminates its child instead of letting
    it compute to a discarded finish."""
    import threading

    marker = tmp_path / "finished.txt"
    dag_id = _submit(
        store,
        TaskSpec(
            name="long",
            executor="shell",
            args={"command": f"sleep 30 && touch {marker}"},
        ),
    )
    done = threading.Event()

    def run_worker():
        ws = Store(store.path)  # sqlite connections are thread-bound
        try:
            Worker(ws, name="iso-w", chips=0, workdir=str(tmp_path),
                   isolate=True, load_jax_executors=False).run_once()
        finally:
            ws.close()
            done.set()

    t = threading.Thread(target=run_worker, daemon=True)
    t.start()
    # wait for the task to go in_progress, then stop it
    own_store = Store(store.path)
    try:
        deadline = time.time() + 20
        tid = _row(store, dag_id, "long")["id"]
        while time.time() < deadline:
            r = own_store.task_row(tid)
            if r["status"] == TaskStatus.IN_PROGRESS.value:
                break
            time.sleep(0.1)
        else:
            pytest.fail("task never started")
        assert own_store.stop_task(tid)
        assert done.wait(timeout=20), "worker did not return after stop"
    finally:
        own_store.close()
    assert _row(store, dag_id, "long")["status"] == TaskStatus.STOPPED.value
    assert not marker.exists()


def test_concurrent_children_via_poll(store, tmp_path):
    """poll() packs two 1-chip tasks onto a 2-chip worker concurrently."""
    dag_id = _submit(
        store,
        TaskSpec(name="a", executor="shell",
                 args={"command": f"sleep 2 && echo a >> {tmp_path}/order"},
                 resources=ResourceSpec(chips=1)),
        TaskSpec(name="b", executor="shell",
                 args={"command": f"sleep 2 && echo b >> {tmp_path}/order"},
                 resources=ResourceSpec(chips=1)),
    )
    w = Worker(store, name="iso-w", chips=2, workdir=str(tmp_path),
               isolate=True, load_jax_executors=False)
    t0 = time.time()
    w.poll()
    assert len(w._children) == 2, "both tasks should spawn in one poll"
    deadline = time.time() + 60
    while time.time() < deadline:
        w.poll()
        statuses = {r["name"]: r["status"] for r in store.task_rows(dag_id)}
        if all(s == TaskStatus.SUCCESS.value for s in statuses.values()):
            break
        time.sleep(0.2)
    else:
        pytest.fail(f"tasks did not finish: {statuses}")
    # serial execution would need >= 2 sleeps of 2 s plus two interpreter
    # startups; concurrency keeps wall clock well under that
    assert time.time() - t0 < 25


def test_child_logs_reach_store_with_relative_paths(tmp_path, monkeypatch):
    """A worker given RELATIVE --db/--workdir (the CLI defaults) must
    still deliver its children's ctx.log/metric writes to the right
    store — the child runs with cwd=workdir, where a relative db path
    would silently open a fresh empty database (found by a real CLI
    drive; results rode the spec file so the bug only ate observability).
    """
    import os

    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.scheduler.worker import Worker

    monkeypatch.chdir(tmp_path)
    store = Store("rel.sqlite")  # deliberately relative
    try:
        helper = tmp_path / "src" / "rl_helper.py"
        helper.parent.mkdir()
        helper.write_text(
            "def check(ctx):\n"
            "    ctx.log('hello-from-child')\n"
            "    ctx.metric('m', 1.5, step=0)\n"
            "    return {'ok': True}\n"
        )
        dag = DagSpec(
            name="rel", project="t",
            tasks=(TaskSpec(name="a", executor="pyfunc", args={
                "target": "rl_helper:check",
                "code_src": str(helper.parent),
                "code_import": [],
            }),),
        )
        dag_id = store.submit_dag(dag)
        store.set_task_status(dag_id, ["a"], TaskStatus.QUEUED)
        w = Worker(store, name="rw", workdir="wk", isolate=True)  # relative
        assert w.run_once() is True
        tid = store.task_rows(dag_id)[0]["id"]
        row = store.task_row(tid)
        assert row["status"] == TaskStatus.SUCCESS.value, row["error"]
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert "hello-from-child" in logs
        assert [list(p) for p in store.metric_series(tid, "m")] == [[0, 1.5]]
        assert not os.path.exists(tmp_path / "wk" / "rel.sqlite"), (
            "child opened a parallel database"
        )
    finally:
        store.close()

"""obs/devprof: the dependency-free xplane reader.

Three layers of evidence:

- hand-encoded wire bytes (a tiny XSpace built field by field) decode
  to exactly the planes/lines/events/names written — the walker's
  varint/length-delimited/map handling is pinned without any profiler
  in the loop;
- the SHIPPED capture fixture (``tests/data/cpu_capture.xplane.pb``, a
  real ``jax.profiler`` CPU capture) parses and attributes: device
  lanes found, busy time positive, kernel names resolved;
- a LIVE capture produced in-test under ``JAX_PLATFORMS=cpu`` parses
  the same way — the fixture can't go stale silently;
- and the package ships no TensorFlow import anywhere (the whole point
  of the reader).
"""

import glob
import os
import struct

import pytest

from mlcomp_tpu.obs import devprof

FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data",
    "cpu_capture.xplane.pb",
)


# --------------------------------------------------- wire-format encoding


def _vint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _field(fn: int, payload: bytes) -> bytes:
    return _vint((fn << 3) | 2) + _vint(len(payload)) + payload


def _vfield(fn: int, v: int) -> bytes:
    return _vint(fn << 3) + _vint(v)


def _tiny_xspace() -> bytes:
    """One device plane, one "XLA Ops" line at timestamp 1000 ns with
    two events (ids 7 and 9), metadata mapping them to op names; plus
    a host plane the device-lane selector must skip."""
    ev7 = _vfield(1, 7) + _vfield(2, 5_000) + _vfield(3, 2_000_000_000)
    ev9 = (_vfield(1, 9) + _vfield(2, 2_500_000_000)
           + _vfield(3, 1_000_000_000))
    line = (
        _field(2, b"XLA Ops") + _vfield(3, 1000)
        + _field(4, ev7) + _field(4, ev9)
    )
    md7 = _field(2, _vfield(1, 7) + _field(2, b"%fusion.42 = f32[8]"))
    md9 = _field(2, _vfield(1, 9) + _field(2, b"%copy.7 = s32[4]"))
    plane = (
        _field(2, b"/device:TPU:0") + _field(3, line)
        + _field(4, _vfield(1, 7) + md7)
        + _field(4, _vfield(1, 9) + md9)
    )
    host_line = _field(2, b"python") + _field(
        4, _vfield(1, 1) + _vfield(2, 0) + _vfield(3, 500_000)
    )
    host = _field(2, b"/host:CPU") + _field(3, host_line)
    return _field(1, plane) + _field(1, host)


def test_wire_walker_decodes_handwritten_xspace():
    planes = devprof.parse_xspace(_tiny_xspace())
    assert [p.name for p in planes] == ["/device:TPU:0", "/host:CPU"]
    dev = planes[0]
    assert [ln.name for ln in dev.lines] == ["XLA Ops"]
    line = dev.lines[0]
    assert line.timestamp_ns == 1000
    assert [(e.name, e.offset_ps, e.duration_ps) for e in line.events] == [
        ("%fusion.42 = f32[8]", 5_000, 2_000_000_000),
        ("%copy.7 = s32[4]", 2_500_000_000, 1_000_000_000),
    ]


def test_device_lane_selection_prefers_device_plane():
    planes = devprof.parse_xspace(_tiny_xspace())
    lanes = devprof.device_lines(planes)
    assert [(p.name, ln.name) for p, ln in lanes] == [
        ("/device:TPU:0", "XLA Ops")
    ]


def test_attribution_on_handwritten_xspace():
    planes = devprof.parse_xspace(_tiny_xspace())
    att = devprof.attribution(planes, wall_ms=10.0)
    # spans [5e3, ~2e9] and [2.5e9, 3.5e9] ps do not overlap:
    # union = 3.0 ms exactly
    assert att["device_time_ms"] == pytest.approx(3.0, abs=1e-4)
    assert att["host_gap_ms"] == pytest.approx(7.0, abs=1e-4)
    names = [k["name"] for k in att["kernels"]]
    assert names == ["fusion", "copy"]  # normalized, duration-ranked


def test_busy_ms_merges_overlapping_lanes():
    # ps intervals: [0, 1ms] and [0.5ms, 2ms] overlap -> 2ms union,
    # plus a disjoint [3ms, 4ms] -> 3ms total
    ivs = [(0, 1_000_000_000, None), (500_000_000, 2_000_000_000, None),
           (3_000_000_000, 4_000_000_000, None)]
    assert devprof.busy_ms(ivs) == pytest.approx(3.0)


def test_varint_overrun_raises():
    with pytest.raises(ValueError):
        devprof.parse_xspace(_field(1, b"\xff" * 11))


def test_truncated_length_delimited_raises():
    bad = _vint((1 << 3) | 2) + _vint(64) + b"short"
    with pytest.raises(ValueError):
        devprof.parse_xspace(bad)


# ------------------------------------------------------- capture fixtures


def test_shipped_cpu_fixture_parses_and_attributes():
    planes = devprof.load_xspace(FIXTURE)
    assert any("/host:CPU" in p.name for p in planes)
    lanes = devprof.device_lines(planes)
    assert lanes, "no device-equivalent lanes found in the CPU capture"
    att = devprof.attribution(planes, wall_ms=1e4)
    assert att["device_time_ms"] > 0
    assert att["kernels"], "no kernels aggregated"
    # the capture traced one jitted x@x+1: its fusion must be visible
    assert any("fusion" in k["name"] for k in att["kernels"])
    spans, dropped = devprof.device_spans_us(planes)
    assert spans and dropped == 0
    t0s = [s[0] for s in spans]
    assert min(t0s) == 0.0  # spans are capture-relative
    assert all(d > 0 for _, d, _ in spans)


def test_live_cpu_capture_parses(tmp_path):
    """End to end under JAX_PLATFORMS=cpu (conftest pins it): produce a
    fresh xplane with jax.profiler, then read it back with the
    dependency-free walker — the acceptance path, no TF anywhere."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    f(x).block_until_ready()
    jax.profiler.start_trace(str(tmp_path))
    try:
        f(x).block_until_ready()
    finally:
        jax.profiler.stop_trace()
    path = devprof.find_xplane(str(tmp_path))
    planes = devprof.load_xspace(path)
    assert planes
    att = devprof.attribution(planes)
    assert att["device_time_ms"] > 0
    assert att["device_events"] > 0


def test_find_xplane_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        devprof.find_xplane(str(tmp_path))


def test_no_tensorflow_import_in_package_or_tools():
    """The reader exists so nothing needs tensorflow.tsl: any import of
    tensorflow anywhere in mlcomp_tpu/ or tools/ is a regression."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    offenders = []
    for sub in ("mlcomp_tpu", "tools"):
        for path in glob.glob(
            os.path.join(root, sub, "**", "*.py"), recursive=True
        ):
            with open(path) as fh:
                for i, ln in enumerate(fh, 1):
                    s = ln.strip()
                    if s.startswith(("import tensorflow",
                                     "from tensorflow")):
                        offenders.append(f"{path}:{i}")
    assert not offenders, f"tensorflow imports found: {offenders}"


def test_parse_with_stats_resolves_refs():
    """XStat decoding: str values pass through, ref values resolve via
    stat_metadata."""
    stat_str = _vfield(1, 3) + _field(5, b"hello")
    stat_ref = _vfield(1, 4) + _vfield(7, 5)
    ev = (_vfield(1, 7) + _vfield(2, 0) + _vfield(3, 10)
          + _field(4, stat_str) + _field(4, stat_ref))
    line = _field(2, b"XLA Ops") + _field(4, ev)
    smd3 = _field(2, _vfield(1, 3) + _field(2, b"note"))
    smd4 = _field(2, _vfield(1, 4) + _field(2, b"kind"))
    smd5 = _field(2, _vfield(1, 5) + _field(2, b"fused_kind"))
    plane = (
        _field(2, b"/device:TPU:0") + _field(3, line)
        + _field(4, _vfield(1, 7) + _field(
            2, _vfield(1, 7) + _field(2, b"op")))
        + _field(5, _vfield(1, 3) + smd3)
        + _field(5, _vfield(1, 4) + smd4)
        + _field(5, _vfield(1, 5) + smd5)
    )
    planes = devprof.parse_xspace(_field(1, plane), with_stats=True)
    ev = planes[0].lines[0].events[0]
    assert ev.stats == {"note": "hello", "kind": "fused_kind"}

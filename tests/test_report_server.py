"""Report server endpoints over a live ephemeral-port HTTP server."""

import json
import urllib.request

import pytest

from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.report.server import start_in_thread


@pytest.fixture()
def served(tmp_db):
    store = Store(tmp_db)
    dag = DagSpec(
        name="demo",
        project="p",
        tasks=(
            TaskSpec(name="a", executor="noop", stage="train"),
            TaskSpec(name="b", executor="noop", stage="valid", depends=("a",)),
        ),
    )
    dag_id = store.submit_dag(dag)
    rows = store.task_rows(dag_id)
    tid = rows[0]["id"]
    store.log(tid, "info", "hello from a")
    store.metric(tid, "train/loss", 0.5, step=0)
    store.metric(tid, "train/loss", 0.25, step=1)
    store.heartbeat("worker-0", chips=8)
    srv, port = start_in_thread(tmp_db)
    yield store, dag_id, tid, port
    srv.shutdown()
    store.close()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read()


def test_dashboard_html(served):
    *_, port = served
    status, body = _get(port, "/")
    assert status == 200 and b"mlcomp-tpu report" in body


def test_api_dags_and_tasks(served):
    _, dag_id, _, port = served
    status, body = _get(port, "/api/dags")
    dags = json.loads(body)
    assert status == 200 and dags[0]["name"] == "demo"
    assert dags[0]["counts"] == {"not_ran": 2}

    status, body = _get(port, f"/api/dags/{dag_id}/tasks")
    tasks = json.loads(body)
    assert [t["name"] for t in tasks] == ["a", "b"]


def test_api_logs_metrics_workers(served):
    _, _, tid, port = served
    _, body = _get(port, f"/api/tasks/{tid}/logs")
    assert json.loads(body)[0]["message"] == "hello from a"

    _, body = _get(port, f"/api/tasks/{tid}/metrics")
    assert json.loads(body) == ["train/loss"]

    _, body = _get(port, f"/api/tasks/{tid}/metrics/train/loss")
    assert json.loads(body) == [[0, 0.5], [1, 0.25]]

    _, body = _get(port, "/api/workers")
    assert json.loads(body)[0]["name"] == "worker-0"


def test_api_404(served):
    *_, port = served
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/api/nothing")
    assert ei.value.code == 404


def test_dashboard_ships_charts_and_graph(served):
    """The dashboard page carries the metric-chart and DAG-graph machinery."""
    *_, port = served
    _, body = _get(port, "/")
    html = body.decode()
    for needle in ("lineChart", "drawGraph", "prefers-color-scheme"):
        assert needle in html, needle


def _post(port, path, headers):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="POST", headers=headers
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def test_post_token_auth(served, monkeypatch):
    """With MLCOMP_TPU_REPORT_TOKEN set, mutation routes demand the Bearer
    token; without the env var they stay open (CSRF header only)."""
    import urllib.error

    _, dag_id, _, port = served
    csrf = {"X-Requested-With": "mlcomp-tpu"}

    monkeypatch.setenv("MLCOMP_TPU_REPORT_TOKEN", "s3cret")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, f"/api/dags/{dag_id}/stop", csrf)
    assert ei.value.code == 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, f"/api/dags/{dag_id}/stop",
              {**csrf, "Authorization": "Bearer wrong"})
    assert ei.value.code == 403
    status, body = _post(
        port, f"/api/dags/{dag_id}/stop",
        {**csrf, "Authorization": "Bearer s3cret"},
    )
    assert status == 200 and "stopped_tasks" in body

    monkeypatch.delenv("MLCOMP_TPU_REPORT_TOKEN")
    status, body = _post(port, f"/api/dags/{dag_id}/restart", csrf)
    assert status == 200 and "reset_tasks" in body


def test_get_token_auth(served, monkeypatch):
    """ADVICE r2: a configured token guards GET data routes too (logs,
    metrics, reports), not just mutations; the static dashboard shell
    stays open (it holds no data)."""
    import urllib.error

    _, _, tid, port = served
    monkeypatch.setenv("MLCOMP_TPU_REPORT_TOKEN", "s3cret")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, f"/api/tasks/{tid}/logs")
    assert ei.value.code == 403

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/tasks/{tid}/logs",
        headers={"Authorization": "Bearer s3cret"},
    )
    with urllib.request.urlopen(req) as r:
        assert r.status == 200
        assert json.loads(r.read())[0]["message"] == "hello from a"

    # the HTML shell itself is served without the token
    status, body = _get(port, "/")
    assert status == 200 and b"token" in body


def test_api_models(served, tmp_path, monkeypatch):
    from mlcomp_tpu.io.storage import ModelStorage

    monkeypatch.setenv("MLCOMP_TPU_STORAGE", str(tmp_path / "models"))
    *_, port = served
    _, body = _get(port, "/api/models")
    assert json.loads(body) == []  # empty root

    ms = ModelStorage(str(tmp_path / "models"))
    (ms.checkpoint_dir("p", "d1", "train") / "7").mkdir()
    ms.write_meta("p", "d1", "train", {"params": 123})
    _, body = _get(port, "/api/models")
    (entry,) = json.loads(body)
    assert entry["project"] == "p" and entry["task"] == "train"
    assert entry["checkpoints"] == ["7"] and entry["updated"] is not None


def test_dag_level_metric_comparison(served):
    """One metric across all tasks of a DAG — the grid-compare endpoint."""
    store, dag_id, tid, port = served
    rows = store.task_rows(dag_id)
    tid_b = rows[1]["id"]
    store.metric(tid_b, "train/loss", 0.8, step=0)
    store.metric(tid_b, "train/loss", 0.4, step=1)

    _, body = _get(port, f"/api/dags/{dag_id}/metrics")
    assert json.loads(body) == ["train/loss"]

    _, body = _get(port, f"/api/dags/{dag_id}/metrics/train/loss")
    by_task = json.loads(body)
    assert by_task["a"] == [[0, 0.5], [1, 0.25]]
    assert by_task["b"] == [[0, 0.8], [1, 0.4]]

    _, body = _get(port, "/")
    html = body.decode()
    for needle in ("multiChart", "refreshCompare", "cmpsel", "seriesColor"):
        assert needle in html, needle


def test_declared_layout_round_trip(served):
    """Round 4 (upstream parity): a task's YAML `report: {layout: [...]}`
    persists as a "layout" artifact the dashboard reads — the API serves
    it back validated, and the dashboard JS ships the layout-aware
    rendering path."""
    from mlcomp_tpu.executors.base import ExecutionContext
    from mlcomp_tpu.report.artifacts import publish_layout

    store, dag_id, tid, port = served
    ctx = ExecutionContext(
        dag_id=dag_id, task_id=tid, task_name="a",
        args={}, store=store,
    )
    assert publish_layout(ctx, {"layout": [
        {"type": "series", "metrics": ["train/loss"], "title": "Loss"},
        "confusion",
        {"type": "gallery"},
    ]})
    status, body = _get(port, f"/api/tasks/{tid}/reports")
    reps = json.loads(body)
    layout = [r for r in reps if r["name"] == "layout"]
    assert status == 200 and len(layout) == 1
    status, body = _get(port, f"/api/reports/{layout[0]['id']}")
    payload = json.loads(body)
    assert payload["kind"] == "layout"
    assert payload["panels"][0] == {
        "type": "series", "metrics": ["train/loss"], "title": "Loss",
    }
    assert payload["panels"][1] == {"type": "confusion"}
    # the dashboard ships the layout-aware renderer
    _, html = _get(port, "/")
    assert b"layout" in html and b"panel.metrics" in html

    # malformed layouts are rejected (logged, not raised) and nothing new
    # is stored
    assert not publish_layout(ctx, {"layout": [{"type": "nope"}]})
    assert not publish_layout(
        ctx, {"layout": [{"type": "series", "metrics": []}]}
    )
    reps2 = json.loads(_get(port, f"/api/tasks/{tid}/reports")[1])
    assert len([r for r in reps2 if r["name"] == "layout"]) == 1
    logs = " ".join(l["message"] for l in store.task_logs(tid))
    assert "layout rejected" in logs


def test_fleet_surfaces_unconfigured_and_unreachable(served, monkeypatch):
    """/fleet/trace + /fleet/metrics: 404 with no daemons configured;
    with an unreachable daemon the merge degrades to an error entry /
    an up=0 row instead of failing the whole scrape.  (The live
    two-daemon merge is covered by tools/obs_check.py.)"""
    import urllib.error

    _, _, _, port = served
    monkeypatch.delenv("MLCOMP_TPU_SERVE_URLS", raising=False)
    monkeypatch.delenv("MLCOMP_TPU_SERVE_URL", raising=False)
    for path in ("/fleet/trace", "/fleet/metrics"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, path)
        assert ei.value.code == 404

    monkeypatch.setenv("MLCOMP_TPU_SERVE_URLS", "http://127.0.0.1:1")
    # malformed filters 400 at the report server BEFORE the fan-out —
    # not N daemon 400s silently merged into an empty 200
    for bad in ("/fleet/trace?trace_id=GARBAGE",
                "/fleet/trace?last_ms=-5",
                "/fleet/trace?last_ms=nope"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, bad)
        assert ei.value.code == 400, bad
    code, body = _get(port, "/fleet/trace")
    assert code == 200
    fleet = json.loads(body)
    assert fleet["traceEvents"] == []
    (d,) = fleet["otherData"]["daemons"]
    assert d["name"] == "127.0.0.1:1" and "error" in d
    code, body = _get(port, "/fleet/metrics")
    assert code == 200
    assert 'mlcomp_fleet_daemon_up{daemon="127.0.0.1:1"} 0' in (
        body.decode()
    )

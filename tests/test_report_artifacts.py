"""Report artifacts: classification/segmentation payload numerics, store
persistence, server endpoints, and the valid-executor wiring."""

import json
import urllib.request

import numpy as np
import pytest

from mlcomp_tpu.db.store import Store
from mlcomp_tpu.report.artifacts import (
    average_precision,
    classification_report,
    confusion_matrix,
    pr_curve,
    segmentation_report,
)


def test_confusion_matrix_counts():
    y_true = np.array([0, 0, 1, 1, 2])
    y_pred = np.array([0, 1, 1, 1, 0])
    cm = confusion_matrix(y_true, y_pred, 3)
    assert cm.tolist() == [[1, 1, 0], [0, 2, 0], [1, 0, 0]]


def test_pr_curve_perfect_ranking():
    # positives scored above all negatives -> precision 1.0 at every recall
    y = np.array([1, 1, 0, 0])
    s = np.array([0.9, 0.8, 0.2, 0.1])
    curve = pr_curve(y, s)
    assert curve[0] == [0.5, 1.0]
    assert [1.0, 1.0] in curve
    assert average_precision(y, s) == pytest.approx(1.0)


def test_pr_curve_no_positives_empty():
    assert pr_curve(np.zeros(4, dtype=int), np.ones(4)) == []
    assert average_precision(np.zeros(4, dtype=int), np.ones(4)) == 0.0


def test_classification_report_payload():
    # 3 classes, one confident mistake (sample 3: true 2 scored as 0)
    y_true = np.array([0, 1, 2, 2])
    probs = np.array(
        [
            [0.8, 0.1, 0.1],
            [0.1, 0.8, 0.1],
            [0.1, 0.1, 0.8],
            [0.9, 0.05, 0.05],
        ]
    )
    rep = classification_report(y_true, probs, class_names=["a", "b", "c"])
    assert rep["kind"] == "classification"
    assert rep["accuracy"] == pytest.approx(0.75)
    assert rep["confusion"][2] == [1, 0, 1]
    by_name = {r["name"]: r for r in rep["per_class"]}
    assert by_name["c"]["recall"] == pytest.approx(0.5)
    assert by_name["c"]["precision"] == pytest.approx(1.0)
    assert by_name["c"]["support"] == 2
    # gallery: the single mistake, confidently wrong
    assert rep["worst"] == [
        {"index": 3, "true": "c", "pred": "a", "confidence": pytest.approx(0.9)}
    ]
    assert set(rep["pr_curves"]) == {"a", "b", "c"}
    assert 0.0 < rep["mean_average_precision"] <= 1.0
    json.dumps(rep)  # payload must be JSON-able


def test_classification_report_accepts_logits():
    y_true = np.array([0, 1])
    logits = np.array([[5.0, -5.0], [-5.0, 5.0]])
    rep = classification_report(y_true, logits)
    assert rep["accuracy"] == 1.0
    assert rep["worst"] == []


def test_segmentation_report_payload():
    y_true = np.zeros((2, 4, 4), dtype=np.int64)
    y_true[:, 2:, :] = 1
    y_pred = np.zeros((2, 4, 4), dtype=np.int64)
    y_pred[:, 1:, :] = 1  # over-predicts class 1 by one row
    rep = segmentation_report(y_true, y_pred, num_classes=2)
    assert rep["kind"] == "segmentation"
    assert rep["pixel_accuracy"] == pytest.approx(0.75)
    by_name = {r["name"]: r for r in rep["per_class"]}
    # class1: tp=16, fp=8, fn=0 -> iou 16/24
    assert by_name["1"]["iou"] == pytest.approx(16 / 24)
    assert by_name["1"]["dice"] == pytest.approx(32 / 40)
    assert 0 < rep["mean_iou"] < 1
    json.dumps(rep)


def test_segmentation_report_argmaxes_probs():
    y_true = np.zeros((1, 2, 2), dtype=np.int64)
    probs = np.zeros((1, 2, 2, 3))
    probs[..., 0] = 1.0
    rep = segmentation_report(y_true, probs, num_classes=3)
    assert rep["pixel_accuracy"] == 1.0


def test_store_report_roundtrip(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    rid = store.add_report(tid, "valid_cls", {"kind": "classification", "n": 4})
    reps = store.reports(tid)
    assert len(reps) == 1 and reps[0]["kind"] == "classification"
    assert store.report_payload(rid)["n"] == 4
    assert store.report_payload(9999) is None
    store.close()


def test_server_report_endpoints(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.report.server import start_in_thread

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    rid = store.add_report(tid, "r", {"kind": "segmentation", "mean_iou": 0.5})
    srv, port = start_in_thread(tmp_db)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/tasks/{tid}/reports"
        ) as r:
            reps = json.loads(r.read())
        assert reps[0]["id"] == rid and reps[0]["kind"] == "segmentation"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/reports/{rid}"
        ) as r:
            assert json.loads(r.read())["mean_iou"] == 0.5
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            html = r.read().decode()
        for needle in ("renderReport", "confusionTable", "PR: "):
            assert needle in html, needle
    finally:
        srv.shutdown()
        store.close()


def test_valid_executor_emits_report(tmp_db):
    """End-to-end: valid task with report: config persists a classification
    payload into the store."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 24,
                "num_classes": 3,
                "dim": 8,
                "batch_size": 8,
            }
        },
        "report": {"kind": "classification", "top_worst": 4},
    }
    ctx = ExecutionContext(
        dag_id=dag_id, task_id=tid, task_name="v", args=cfg, store=store
    )
    ok, result, err = run_task("valid", ctx)
    assert ok, err
    reps = store.reports(tid)
    assert len(reps) == 1 and reps[0]["kind"] == "classification"
    payload = store.report_payload(reps[0]["id"])
    assert payload["n"] == 24 and len(payload["confusion"]) == 3
    assert len(payload["worst"]) <= 4
    store.close()


def test_names_padded_when_class_list_short():
    y_true = np.array([0, 1, 2])
    probs = np.eye(3)
    rep = classification_report(y_true, probs, class_names=["a", "b"])
    assert rep["class_names"] == ["a", "b", "2"]
    seg = segmentation_report(
        np.array([[[0, 2]]]), np.array([[[0, 2]]]), class_names=["bg"]
    )
    assert seg["class_names"] == ["bg", "1", "2"]


def test_predict_labels_align_under_shuffle():
    """Labels returned by predict come from the same (shuffled) batches."""
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 30,
                "num_classes": 3,
                "dim": 8,
                "batch_size": 8,
                "shuffle": True,
            }
        },
    }
    t = Trainer(cfg)
    preds, labels = t.predict("valid", return_labels=True)
    assert preds.shape[0] == labels.shape[0] == 30
    # the dataset's label multiset must survive the shuffle round-trip
    orig = np.sort(np.asarray(t.loaders["valid"].data["y"]))
    assert np.array_equal(np.sort(labels), orig)


def test_classification_report_stray_and_ignore_labels():
    """Labels outside [0, n_scored) widen the confusion matrix; negative
    labels are treated as ignore-index and dropped."""
    y_true = np.array([0, 1, 3, -1])  # 3 is beyond the 3-wide head; -1 ignored
    probs = np.eye(3)[[0, 1, 2, 0]]
    rep = classification_report(y_true, probs)
    assert rep["n"] == 3
    assert len(rep["confusion"]) == 4  # widened to cover stray class 3
    assert rep["confusion"][3][2] == 1  # stray true=3 predicted as 2
    assert set(rep["pr_curves"]) <= {"0", "1", "2"}  # only scored classes


def test_segmentation_ignore_labels():
    """Negative and explicit ignore labels are excluded from pixel stats."""
    y_true = np.array([[[0, 1], [-1, 255]]])
    y_pred = np.array([[[0, 1], [0, 1]]])
    rep = segmentation_report(y_true, y_pred, num_classes=2, ignore_label=255)
    assert rep["n_pixels"] == 2  # -1 and 255 dropped
    assert rep["pixel_accuracy"] == 1.0
    assert len(rep["confusion"]) == 2


def test_segmentation_report_from_confusion_matches():
    from mlcomp_tpu.report.artifacts import segmentation_report_from_confusion

    y_true = np.random.RandomState(0).randint(0, 3, (2, 8, 8))
    y_pred = np.random.RandomState(1).randint(0, 3, (2, 8, 8))
    direct = segmentation_report(y_true, y_pred, num_classes=3)
    cm = confusion_matrix(y_true.ravel(), y_pred.ravel(), 3)
    streamed = segmentation_report_from_confusion(cm)
    assert direct["mean_iou"] == streamed["mean_iou"]
    assert direct["confusion"] == streamed["confusion"]


def test_report_path_metrics_match_eval_epoch(tmp_db):
    """Enabling report: must not change the logged metric values, including
    with a ragged (padded) tail batch."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    data = {
        "name": "synthetic_classification",
        "n": 30,  # batch 8 -> ragged tail of 6
        "num_classes": 3,
        "dim": 8,
        "batch_size": 8,
        "drop_last": False,
    }
    base = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "seed": 7,
        "data": {"valid": data},
    }
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(
            name="d",
            project="p",
            tasks=(
                TaskSpec(name="plain", executor="valid"),
                TaskSpec(name="rep", executor="valid"),
            ),
        )
    )
    rows = {r["name"]: r["id"] for r in store.task_rows(dag_id)}
    ok1, res_plain, err1 = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=rows["plain"], task_name="plain",
                         args=dict(base), store=store),
    )
    with_rep = dict(base)
    with_rep["report"] = {"kind": "classification"}
    ok2, res_rep, err2 = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=rows["rep"], task_name="rep",
                         args=with_rep, store=store),
    )
    assert ok1 and ok2, (err1, err2)
    assert res_plain["loss"] == pytest.approx(res_rep["loss"], rel=1e-5)
    assert res_plain["accuracy"] == pytest.approx(res_rep["accuracy"], rel=1e-5)
    assert len(store.reports(rows["rep"])) == 1
    store.close()


def test_report_truncates_at_max_samples(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 24,
                "num_classes": 3,
                "dim": 8,
                "batch_size": 8,
            }
        },
        "report": {"kind": "classification", "max_samples": 10},
    }
    ok, _, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok, err
    payload = store.report_payload(store.reports(tid)[0]["id"])
    assert payload["n"] == 10 and payload["truncated_to"] == 10
    store.close()


def test_unknown_report_kind_falls_back_with_error_log(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 16, "num_classes": 3, "dim": 8, "batch_size": 8,
            }
        },
        "report": {"kind": "cls"},  # typo'd kind
    }
    ok, res, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok and "loss" in res
    assert store.reports(tid) == []
    msgs = [l["message"] for l in store.task_logs(tid)]
    assert any("unknown report kind" in m for m in msgs), msgs
    store.close()


def test_widened_sum_pads_confusion():
    from mlcomp_tpu.executors.infer import _widened_sum

    a = np.array([[1, 0], [0, 1]])
    b = np.array([[1, 0, 0], [0, 0, 0], [0, 0, 2]])
    s = _widened_sum(a, b)
    assert s.tolist() == [[2, 0, 0], [0, 1, 0], [0, 0, 2]]
    assert _widened_sum(b, a).tolist() == s.tolist()


def test_empty_report_dict_enables_defaults(tmp_db):
    """report: {} means 'report with defaults', not 'disabled'."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 16, "num_classes": 3, "dim": 8, "batch_size": 8,
            }
        },
        "report": {},
    }
    ok, _, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok, err
    assert len(store.reports(tid)) == 1
    store.close()


def test_gallery_indices_survive_filtering():
    """Gallery indices refer to caller-supplied positions, unshifted by
    ignore filtering."""
    y_true = np.array([0, 1, 0])
    probs = np.array([[0.9, 0.1], [0.95, 0.05], [0.2, 0.8]])  # 1,2 wrong
    rep = classification_report(
        y_true, probs, sample_indices=np.array([10, 20, 30])
    )
    assert sorted(w["index"] for w in rep["worst"]) == [20, 30]


def test_large_class_count_omits_confusion_and_caps_curves():
    rng = np.random.default_rng(0)
    n_cls = 100
    y = rng.integers(0, n_cls, 512)
    probs = rng.random((512, n_cls))
    rep = classification_report(y, probs)
    assert rep["confusion"] is None
    assert len(rep["pr_curves"]) <= 32
    assert len(rep["average_precision"]) > 32  # AP still for all classes
    assert len(rep["per_class"]) == n_cls


def test_report_string_shorthand_and_onehot_labels(tmp_db, tmp_path):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 16, "num_classes": 3, "dim": 8, "batch_size": 8,
                "one_hot": True,
            }
        },
        "report": "classification",  # string shorthand
    }
    from mlcomp_tpu.data.datasets import create_dataset

    # one-hot labels: rebuild the dataset arrays by hand if the generator
    # doesn't support one_hot natively
    ds = create_dataset(cfg["data"]["valid"])
    if ds["y"].ndim == 1:
        onehot = np.eye(3, dtype=np.float32)[ds["y"]]
        npz_path = str(tmp_path / "onehot_valid.npz")
        np.savez(npz_path, x=ds["x"], y=onehot)
        cfg["data"]["valid"] = {"name": "npz", "path": npz_path, "batch_size": 8}
    ok, res, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok, err
    reps = store.reports(tid)
    assert len(reps) == 1 and reps[0]["kind"] == "classification", (
        [l["message"] for l in store.task_logs(tid)]
    )
    payload = store.report_payload(reps[0]["id"])
    assert payload["n"] == 16
    store.close()


def test_truncation_budget_counts_filtered_rows(tmp_db, tmp_path):
    """max_samples fills with ELIGIBLE rows; ignore-filtered rows don't
    consume budget, and truncated_to reports what was actually kept."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    rng = np.random.default_rng(0)
    x = rng.random((24, 8), dtype=np.float32)
    y = rng.integers(0, 3, 24)
    y[::2] = 9  # half the rows carry the ignore label
    npz_path = str(tmp_path / "v.npz")
    np.savez(npz_path, x=x, y=y)

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {"valid": {"name": "npz", "path": npz_path, "batch_size": 8}},
        "report": {"kind": "classification", "max_samples": 10,
                   "ignore_label": 9},
    }
    ok, _, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok, err
    payload = store.report_payload(store.reports(tid)[0]["id"])
    # 12 eligible rows, budget 10 -> exactly 10 kept, flagged truncated
    assert payload["n"] == 10 and payload["truncated_to"] == 10
    store.close()


def test_segmentation_confusion_capped():
    from mlcomp_tpu.report.artifacts import segmentation_report_from_confusion

    big = np.eye(100, dtype=np.int64)
    rep = segmentation_report_from_confusion(big)
    assert rep["confusion"] is None and len(rep["per_class"]) == 100


def test_legacy_store_schema_migrates_to_nullable_metrics(tmp_path):
    """Old DBs with metrics.value NOT NULL are rebuilt on open."""
    import sqlite3

    path = str(tmp_path / "legacy.sqlite")
    conn = sqlite3.connect(path)
    conn.executescript(
        """
        CREATE TABLE metrics (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            task_id INTEGER NOT NULL, ts REAL NOT NULL,
            name TEXT NOT NULL, step INTEGER NOT NULL DEFAULT 0,
            value REAL NOT NULL
        );
        INSERT INTO metrics (task_id, ts, name, step, value)
            VALUES (1, 0.0, 'loss', 0, 0.5);
        """
    )
    conn.commit()
    conn.close()
    store = Store(path)
    store.metric(1, "loss", float("nan"), step=1)  # legacy schema would raise
    assert store.metric_series(1, "loss") == [(0, 0.5)]
    store.metric(1, "loss", 0.25, step=2)
    assert store.metric_series(1, "loss") == [(0, 0.5), (2, 0.25)]
    store.close()


def test_report_all_rows_ignored_keeps_stats(tmp_db, tmp_path):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    rng = np.random.default_rng(0)
    npz_path = str(tmp_path / "v.npz")
    np.savez(npz_path, x=rng.random((16, 8), dtype=np.float32),
             y=np.full(16, 7))  # every label ignored
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {"valid": {"name": "npz", "path": npz_path, "batch_size": 8}},
        "report": {"kind": "classification", "ignore_label": 7},
    }
    ok, res, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok, err
    assert store.reports(tid) == []
    msgs = [l["message"] for l in store.task_logs(tid)]
    assert any("no eligible samples" in m for m in msgs), msgs
    store.close()


def test_seg_ignore_label_does_not_widen_confusion(tmp_db, tmp_path):
    """Pre-argmaxed masks with 255 void labels keep the true class count."""
    from mlcomp_tpu.executors.infer import _widened_sum  # noqa: F401
    from mlcomp_tpu.report.artifacts import segmentation_report

    y_true = np.array([[[0, 1], [255, 2]]])
    y_pred = np.array([[[0, 1], [0, 2]]])
    rep = segmentation_report(y_true, y_pred, ignore_label=255)
    assert len(rep["confusion"]) == 3

"""Report artifacts: classification/segmentation payload numerics, store
persistence, server endpoints, and the valid-executor wiring."""

import json
import urllib.request

import numpy as np
import pytest

from mlcomp_tpu.db.store import Store
from mlcomp_tpu.report.artifacts import (
    average_precision,
    classification_report,
    confusion_matrix,
    pr_curve,
    segmentation_report,
)


def test_confusion_matrix_counts():
    y_true = np.array([0, 0, 1, 1, 2])
    y_pred = np.array([0, 1, 1, 1, 0])
    cm = confusion_matrix(y_true, y_pred, 3)
    assert cm.tolist() == [[1, 1, 0], [0, 2, 0], [1, 0, 0]]


def test_pr_curve_perfect_ranking():
    # positives scored above all negatives -> precision 1.0 at every recall
    y = np.array([1, 1, 0, 0])
    s = np.array([0.9, 0.8, 0.2, 0.1])
    curve = pr_curve(y, s)
    assert curve[0] == [0.5, 1.0]
    assert [1.0, 1.0] in curve
    assert average_precision(y, s) == pytest.approx(1.0)


def test_pr_curve_no_positives_empty():
    assert pr_curve(np.zeros(4, dtype=int), np.ones(4)) == []
    assert average_precision(np.zeros(4, dtype=int), np.ones(4)) == 0.0


def test_classification_report_payload():
    # 3 classes, one confident mistake (sample 3: true 2 scored as 0)
    y_true = np.array([0, 1, 2, 2])
    probs = np.array(
        [
            [0.8, 0.1, 0.1],
            [0.1, 0.8, 0.1],
            [0.1, 0.1, 0.8],
            [0.9, 0.05, 0.05],
        ]
    )
    rep = classification_report(y_true, probs, class_names=["a", "b", "c"])
    assert rep["kind"] == "classification"
    assert rep["accuracy"] == pytest.approx(0.75)
    assert rep["confusion"][2] == [1, 0, 1]
    by_name = {r["name"]: r for r in rep["per_class"]}
    assert by_name["c"]["recall"] == pytest.approx(0.5)
    assert by_name["c"]["precision"] == pytest.approx(1.0)
    assert by_name["c"]["support"] == 2
    # gallery: the single mistake, confidently wrong
    assert rep["worst"] == [
        {"index": 3, "true": "c", "pred": "a", "confidence": pytest.approx(0.9)}
    ]
    assert set(rep["pr_curves"]) == {"a", "b", "c"}
    assert 0.0 < rep["mean_average_precision"] <= 1.0
    json.dumps(rep)  # payload must be JSON-able


def test_classification_report_accepts_logits():
    y_true = np.array([0, 1])
    logits = np.array([[5.0, -5.0], [-5.0, 5.0]])
    rep = classification_report(y_true, logits)
    assert rep["accuracy"] == 1.0
    assert rep["worst"] == []


def test_segmentation_report_payload():
    y_true = np.zeros((2, 4, 4), dtype=np.int64)
    y_true[:, 2:, :] = 1
    y_pred = np.zeros((2, 4, 4), dtype=np.int64)
    y_pred[:, 1:, :] = 1  # over-predicts class 1 by one row
    rep = segmentation_report(y_true, y_pred, num_classes=2)
    assert rep["kind"] == "segmentation"
    assert rep["pixel_accuracy"] == pytest.approx(0.75)
    by_name = {r["name"]: r for r in rep["per_class"]}
    # class1: tp=16, fp=8, fn=0 -> iou 16/24
    assert by_name["1"]["iou"] == pytest.approx(16 / 24)
    assert by_name["1"]["dice"] == pytest.approx(32 / 40)
    assert 0 < rep["mean_iou"] < 1
    json.dumps(rep)


def test_segmentation_report_argmaxes_probs():
    y_true = np.zeros((1, 2, 2), dtype=np.int64)
    probs = np.zeros((1, 2, 2, 3))
    probs[..., 0] = 1.0
    rep = segmentation_report(y_true, probs, num_classes=3)
    assert rep["pixel_accuracy"] == 1.0


def test_store_report_roundtrip(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    rid = store.add_report(tid, "valid_cls", {"kind": "classification", "n": 4})
    reps = store.reports(tid)
    assert len(reps) == 1 and reps[0]["kind"] == "classification"
    assert store.report_payload(rid)["n"] == 4
    assert store.report_payload(9999) is None
    store.close()


def test_server_report_endpoints(tmp_db):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.report.server import start_in_thread

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    rid = store.add_report(tid, "r", {"kind": "segmentation", "mean_iou": 0.5})
    srv, port = start_in_thread(tmp_db)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/tasks/{tid}/reports"
        ) as r:
            reps = json.loads(r.read())
        assert reps[0]["id"] == rid and reps[0]["kind"] == "segmentation"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/reports/{rid}"
        ) as r:
            assert json.loads(r.read())["mean_iou"] == 0.5
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            html = r.read().decode()
        for needle in ("renderReport", "confusionTable", "PR: "):
            assert needle in html, needle
    finally:
        srv.shutdown()
        store.close()


def test_valid_executor_emits_report(tmp_db):
    """End-to-end: valid task with report: config persists a classification
    payload into the store."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 24,
                "num_classes": 3,
                "dim": 8,
                "batch_size": 8,
            }
        },
        "report": {"kind": "classification", "top_worst": 4},
    }
    ctx = ExecutionContext(
        dag_id=dag_id, task_id=tid, task_name="v", args=cfg, store=store
    )
    ok, result, err = run_task("valid", ctx)
    assert ok, err
    reps = store.reports(tid)
    assert len(reps) == 1 and reps[0]["kind"] == "classification"
    payload = store.report_payload(reps[0]["id"])
    assert payload["n"] == 24 and len(payload["confusion"]) == 3
    assert len(payload["worst"]) <= 4
    store.close()


def test_names_padded_when_class_list_short():
    y_true = np.array([0, 1, 2])
    probs = np.eye(3)
    rep = classification_report(y_true, probs, class_names=["a", "b"])
    assert rep["class_names"] == ["a", "b", "2"]
    seg = segmentation_report(
        np.array([[[0, 2]]]), np.array([[[0, 2]]]), class_names=["bg"]
    )
    assert seg["class_names"] == ["bg", "1", "2"]


def test_predict_labels_align_under_shuffle():
    """Labels returned by predict come from the same (shuffled) batches."""
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {
                "name": "synthetic_classification",
                "n": 30,
                "num_classes": 3,
                "dim": 8,
                "batch_size": 8,
                "shuffle": True,
            }
        },
    }
    t = Trainer(cfg)
    preds, labels = t.predict("valid", return_labels=True)
    assert preds.shape[0] == labels.shape[0] == 30
    # the dataset's label multiset must survive the shuffle round-trip
    orig = np.sort(np.asarray(t.loaders["valid"].data["y"]))
    assert np.array_equal(np.sort(labels), orig)


def test_classification_report_stray_and_ignore_labels():
    """Labels outside [0, n_scored) widen the confusion matrix; negative
    labels are treated as ignore-index and dropped."""
    y_true = np.array([0, 1, 3, -1])  # 3 is beyond the 3-wide head; -1 ignored
    probs = np.eye(3)[[0, 1, 2, 0]]
    rep = classification_report(y_true, probs)
    assert rep["n"] == 3
    assert len(rep["confusion"]) == 4  # widened to cover stray class 3
    assert rep["confusion"][3][2] == 1  # stray true=3 predicted as 2
    assert set(rep["pr_curves"]) <= {"0", "1", "2"}  # only scored classes

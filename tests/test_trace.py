"""Span tracer: Chrome trace output + Trainer integration, plus the
trace-context helpers (W3C trace ids, traceparent parsing, export
clock stamps, per-request export filtering)."""

import json
import time

import pytest

from mlcomp_tpu.utils.trace import (
    Tracer,
    filter_export,
    get_tracer,
    make_trace_id,
    parse_traceparent,
    set_tracer,
    valid_trace_id,
)


def test_spans_and_counters_roundtrip(tmp_path):
    path = str(tmp_path / "t.json")
    tr = Tracer(path)
    with tr.span("outer", epoch=0):
        with tr.span("inner"):
            pass
        tr.instant("marker", note="hi")
    tr.counter("loss", {"train": 1.5})
    out = tr.save()
    body = json.loads(open(out).read())
    evs = body["traceEvents"]
    by_name = {e["name"]: e for e in evs}
    assert by_name["outer"]["ph"] == "X" and by_name["outer"]["dur"] >= 0
    assert by_name["outer"]["args"] == {"epoch": 0}
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]
    assert by_name["marker"]["ph"] == "i"
    assert by_name["loss"]["ph"] == "C"
    assert by_name["loss"]["args"] == {"train": 1.5}


def test_null_tracer_is_silent():
    set_tracer(None)
    t = get_tracer()
    with t.span("x"):
        t.instant("y")
        t.counter("z", {"a": 1})
    with pytest.raises(ValueError):
        t.save()


def test_set_get_tracer():
    tr = Tracer()
    set_tracer(tr)
    assert get_tracer() is tr
    set_tracer(None)
    assert get_tracer() is not tr


def test_make_and_validate_trace_ids():
    tid = make_trace_id()
    assert valid_trace_id(tid) and len(tid) == 32
    assert make_trace_id() != tid  # 128 random bits
    assert not valid_trace_id("0" * 32)   # all-zero is reserved
    assert not valid_trace_id("XY" * 16)  # hex only
    assert not valid_trace_id(tid[:-1])   # length
    assert not valid_trace_id(tid + "\n")  # '$' would accept this
    assert not valid_trace_id(None)


def test_parse_traceparent():
    tid = "0af7651916cd43dd8448eb211c80319c"
    good = f"00-{tid}-00f067aa0ba902b7-01"
    assert parse_traceparent(good) == tid
    assert parse_traceparent(good.upper()) == tid  # case-insensitive
    # malformed headers yield None (mint instead), never raise
    for bad in (None, "", "garbage", f"ff-{tid}-00f067aa0ba902b7-01",
                f"00-{'0' * 32}-00f067aa0ba902b7-01",
                f"00-{tid}-{'0' * 16}-01", f"00-{tid}"):
        assert parse_traceparent(bad) is None


def test_export_carries_clock_stamps():
    tr = Tracer()
    with tr.span("x"):
        pass
    before = time.time() * 1e6
    body = tr.export()
    after = time.time() * 1e6
    od = body["otherData"]
    assert before <= od["export_unix_us"] <= after
    # the offset maps any event ts onto unix time
    ev = body["traceEvents"][0]
    unix = ev["ts"] + od["clock_offset_us"]
    assert abs(unix - od["export_unix_us"]) < 10e6


def test_filter_export_by_trace_id_and_rid():
    tid = make_trace_id()
    tr = Tracer()
    tr.async_begin("request", 7, cat="req", trace_id=tid)
    tr.async_instant("admit", 7, cat="req")
    with tr.span("insert", track="engine.loop", rid=7, trace_id=tid):
        pass
    # a neighbor request and request-agnostic engine spans
    tr.async_begin("request", 8, cat="req", trace_id=make_trace_id())
    with tr.span("issue", track="engine.loop", seq=1):
        pass
    tr.async_end("request", 7, cat="req")
    body = tr.export()
    by_tid = filter_export(body, trace_id=tid)
    non_meta = [e for e in by_tid["traceEvents"] if e["ph"] != "M"]
    assert [e["name"] for e in non_meta] == [
        "request", "admit", "insert", "request"
    ]
    assert by_tid["otherData"]["filter"]["rids"] == [7]
    # rid filter selects the same set; track metadata survives both
    by_rid = filter_export(body, rid=7)
    assert [e["name"] for e in by_rid["traceEvents"] if e["ph"] != "M"
            ] == [e["name"] for e in non_meta]
    assert any(e["ph"] == "M" for e in by_rid["traceEvents"])
    # an unknown id filters everything request-scoped out
    empty = filter_export(body, trace_id=make_trace_id())
    assert [e for e in empty["traceEvents"] if e["ph"] != "M"] == []


def test_trainer_writes_trace(tmp_path):
    from mlcomp_tpu.train.loop import Trainer

    path = str(tmp_path / "train_trace.json")
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 4},
        "optimizer": {"name": "sgd", "lr": 0.1},
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": 2,
        "seed": 0,
        "trace": {"path": path},
        "data": {
            "train": {
                "name": "synthetic_classification",
                "n": 16,
                "dim": 6,
                "num_classes": 4,
                "batch_size": 8,
            }
        },
    }
    trainer = Trainer(cfg)
    trainer.fit()
    set_tracer(None)
    evs = json.loads(open(path).read())["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"train_epoch", "data", "step", "loss"} <= names
    epochs = [e["args"]["epoch"] for e in evs if e["name"] == "train_epoch"]
    assert epochs == [0, 1]

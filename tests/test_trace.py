"""Span tracer: Chrome trace output + Trainer integration."""

import json

import pytest

from mlcomp_tpu.utils.trace import Tracer, get_tracer, set_tracer


def test_spans_and_counters_roundtrip(tmp_path):
    path = str(tmp_path / "t.json")
    tr = Tracer(path)
    with tr.span("outer", epoch=0):
        with tr.span("inner"):
            pass
        tr.instant("marker", note="hi")
    tr.counter("loss", {"train": 1.5})
    out = tr.save()
    body = json.loads(open(out).read())
    evs = body["traceEvents"]
    by_name = {e["name"]: e for e in evs}
    assert by_name["outer"]["ph"] == "X" and by_name["outer"]["dur"] >= 0
    assert by_name["outer"]["args"] == {"epoch": 0}
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]
    assert by_name["marker"]["ph"] == "i"
    assert by_name["loss"]["ph"] == "C"
    assert by_name["loss"]["args"] == {"train": 1.5}


def test_null_tracer_is_silent():
    set_tracer(None)
    t = get_tracer()
    with t.span("x"):
        t.instant("y")
        t.counter("z", {"a": 1})
    with pytest.raises(ValueError):
        t.save()


def test_set_get_tracer():
    tr = Tracer()
    set_tracer(tr)
    assert get_tracer() is tr
    set_tracer(None)
    assert get_tracer() is not tr


def test_trainer_writes_trace(tmp_path):
    from mlcomp_tpu.train.loop import Trainer

    path = str(tmp_path / "train_trace.json")
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 4},
        "optimizer": {"name": "sgd", "lr": 0.1},
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": 2,
        "seed": 0,
        "trace": {"path": path},
        "data": {
            "train": {
                "name": "synthetic_classification",
                "n": 16,
                "dim": 6,
                "num_classes": 4,
                "batch_size": 8,
            }
        },
    }
    trainer = Trainer(cfg)
    trainer.fit()
    set_tracer(None)
    evs = json.loads(open(path).read())["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"train_epoch", "data", "step", "loss"} <= names
    epochs = [e["args"]["epoch"] for e in evs if e["name"] == "train_epoch"]
    assert epochs == [0, 1]

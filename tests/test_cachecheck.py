"""Tier-1 wiring of tools/cachecheck.py: a short fault-injection run
(randomized submit/insert/retire/evict interleavings against the prefix
index, structural + pinning + byte-budget invariants after every op)
plus the multi-threaded concurrent-eviction race.  Pure host code — no
JAX — so the whole file runs in well under a second."""

import pathlib
import sys

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parents[1] / "tools")
)

import cachecheck  # noqa: E402


def test_cachecheck_single_threaded_under_pressure():
    ops = cachecheck.run(seed=0, iters=800, max_bytes=1 << 11)
    # the run must actually exercise every operation class
    assert all(ops[k] > 0 for k in ops), ops


def test_cachecheck_model_checked_no_eviction():
    # generous budget -> nothing evicts -> lookup lengths are checked
    # against the brute-force longest-common-prefix model
    cachecheck.run(seed=1, iters=800, max_bytes=1 << 30,
                   check_model=True)


def test_cachecheck_concurrent_eviction_race():
    cachecheck.run_threaded(seed=2, iters=300, threads=4,
                            max_bytes=1 << 11)


def test_cachecheck_cli_entrypoint():
    assert cachecheck.main(["--iters", "100"]) == 0

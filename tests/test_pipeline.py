"""GPipe collective pipeline vs sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh
from mlcomp_tpu.parallel.pipeline import (
    interleave_stage_params,
    pipeline_apply,
    stack_stage_params,
)


def _stage_fn(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def _make_params(n_stages, dim, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "w": jnp.asarray(rng.normal(scale=0.5, size=(dim, dim)), jnp.float32),
            "b": jnp.asarray(rng.normal(scale=0.1, size=(dim,)), jnp.float32),
        }
        for _ in range(n_stages)
    ]


def _sequential(params_list, x):
    h = x
    for p in params_list:
        h = _stage_fn(p, h)
    return h


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    mesh = make_mesh(MeshSpec(pp=4))
    dim, batch = 16, 16
    params = _make_params(4, dim)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(1).normal(size=(batch, dim)), jnp.float32)

    out = jax.jit(
        lambda sp, x: pipeline_apply(_stage_fn, sp, x, n_micro, mesh)
    )(stacked, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match():
    mesh = make_mesh(MeshSpec(pp=4))
    dim, batch = 8, 8
    params = _make_params(4, dim, seed=2)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(3).normal(size=(batch, dim)), jnp.float32)

    def loss_pipe(sp):
        return jnp.sum(pipeline_apply(_stage_fn, sp, x, 4, mesh) ** 2)

    def loss_seq(params_list):
        return jnp.sum(_sequential(params_list, x) ** 2)

    gp = jax.jit(jax.grad(loss_pipe))(stacked)
    gs = jax.grad(loss_seq)(params)
    gs_stacked = stack_stage_params(gs)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("n_virtual,n_micro", [(2, 4), (2, 8), (3, 4), (2, 6)])
def test_interleaved_pipeline_matches_sequential(n_virtual, n_micro):
    """v virtual stages per device (circular schedule) == sequential apply.

    (2, 6) exercises a microbatch count that is NOT a multiple of the stage
    count — correctness must hold even though the schedule wastes slots.
    """
    mesh = make_mesh(MeshSpec(pp=4))
    dim, batch = 16, 24
    params = _make_params(4 * n_virtual, dim, seed=4)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(5).normal(size=(batch, dim)), jnp.float32)

    out = jax.jit(
        lambda sp, x: pipeline_apply(_stage_fn, sp, x, n_micro, mesh)
    )(stacked, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_interleaved_pipeline_grads_match():
    mesh = make_mesh(MeshSpec(pp=4))
    dim, batch = 8, 8
    params = _make_params(8, dim, seed=6)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(7).normal(size=(batch, dim)), jnp.float32)

    def loss_pipe(sp):
        return jnp.sum(pipeline_apply(_stage_fn, sp, x, 4, mesh) ** 2)

    def loss_seq(params_list):
        return jnp.sum(_sequential(params_list, x) ** 2)

    gp = jax.jit(jax.grad(loss_pipe))(stacked)
    gs_stacked = stack_stage_params(jax.grad(loss_seq)(params))
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pre_interleaved_params_match_network_order():
    """Storing params device-ordered (no per-step gather) gives the same
    result as the default network-ordered path."""
    mesh = make_mesh(MeshSpec(pp=4))
    dim, batch = 8, 8
    params = _make_params(8, dim, seed=8)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(9).normal(size=(batch, dim)), jnp.float32)

    ref = pipeline_apply(_stage_fn, stacked, x, 4, mesh)
    device_ordered = interleave_stage_params(stacked, 4)
    out = pipeline_apply(
        _stage_fn, device_ordered, x, 4, mesh, pre_interleaved=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_pipeline_rejects_non_multiple_virtual_stages():
    mesh = make_mesh(MeshSpec(pp=4))
    params = stack_stage_params(_make_params(6, 8))
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, params, x, 4, mesh)


def test_pipeline_rejects_ragged_microbatches():
    mesh = make_mesh(MeshSpec(pp=4))
    params = stack_stage_params(_make_params(4, 8))
    x = jnp.zeros((10, 8))
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, params, x, 4, mesh)

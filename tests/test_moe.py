"""MoE routing correctness + ep-sharded training on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.moe import MoEBlock


def test_moe_block_routes_and_sows_aux():
    block = MoEBlock(n_experts=4, d_model=16, d_ff=32, k=2,
                     capacity_factor=2.0, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).normal(size=(2, 8, 16)), jnp.float32)
    variables = dict(block.init(jax.random.PRNGKey(0), x))
    variables.pop("losses", None)  # same as train.state.init_model
    out, state = block.apply(variables, x, train=True, mutable=["losses"])
    assert out.shape == x.shape
    aux = jax.tree.leaves(state["losses"])
    assert len(aux) == 1 and np.isfinite(float(aux[0]))
    # with generous capacity almost no tokens drop; output should be nonzero
    assert float(jnp.abs(out).mean()) > 1e-4


def test_moe_capacity_drops_tokens():
    # TRAINING with capacity 1 slot/expert: most tokens dropped -> output
    # rows mostly zero.  INFERENCE routes densely: same block, same tiny
    # capacity factor, but no token may be dropped (KV-cache decode parity
    # depends on this).
    block = MoEBlock(n_experts=2, d_model=8, d_ff=16, k=1,
                     capacity_factor=0.1, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).normal(size=(1, 32, 8)), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    out, _ = block.apply(variables, x, train=True, mutable=["losses"])
    row_norms = np.asarray(jnp.linalg.norm(out[0], axis=-1))
    assert (row_norms < 1e-6).sum() >= 28  # ~2 slots of 32 survive
    dense = block.apply(variables, x, train=False)
    dense_norms = np.asarray(jnp.linalg.norm(dense[0], axis=-1))
    assert (dense_norms > 1e-6).all()  # drop-free at inference


def test_moe_lm_forward():
    model = create_model({
        "name": "moe_lm", "vocab_size": 64, "hidden": 32, "layers": 2,
        "heads": 4, "n_experts": 4, "d_ff": 64, "moe_every": 2,
        "dtype": "float32",
    })
    x = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 16)))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 16, 64)


def test_moe_lm_trains_with_ep_sharding():
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "moe_lm", "vocab_size": 64, "hidden": 32,
                  "layers": 2, "heads": 4, "n_experts": 4, "d_ff": 64,
                  "moe_every": 2, "dtype": "float32"},
        "optimizer": {"name": "adam", "lr": 1e-3},
        "loss": "lm_cross_entropy",
        "metrics": [],
        "epochs": 1,
        "mesh": {"dp": 2, "ep": 4},
        "data": {
            "train": {"name": "synthetic_tokens", "n": 32, "seq_len": 16,
                      "vocab_size": 64, "batch_size": 16},
        },
    }
    tr = Trainer(cfg)
    w1 = tr.state.params["MoELayer_0"]["moe"]["experts_w1"]
    assert "ep" in w1.sharding.spec, w1.sharding.spec
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_moe_dense_einsum_matches_scan_to_tolerance():
    """r4 advisor (low): the t<=64 dense einsum and the per-expert scan
    accumulate the combine in different float orders, so a token decoded
    one step at a time (einsum path) tracks its full-forward value (scan
    path at t>64) to dtype tolerance — not bit-exactly.  Dense routing
    is per-token, so the same token in a longer batch routes the same."""
    block = MoEBlock(n_experts=4, d_model=16, d_ff=32, k=2,
                     capacity_factor=2.0, dtype=jnp.float32)
    x_long = jnp.asarray(
        np.random.RandomState(7).normal(size=(1, 96, 16)), jnp.float32
    )
    variables = block.init(jax.random.PRNGKey(0), x_long)
    out_scan = block.apply(variables, x_long, train=False)       # t=96: scan
    out_einsum = block.apply(variables, x_long[:, :32], train=False)  # t=32
    np.testing.assert_allclose(
        np.asarray(out_einsum), np.asarray(out_scan[:, :32]),
        rtol=2e-5, atol=2e-5,
    )

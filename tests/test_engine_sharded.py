"""Sharded serving: the dispatch pipeline, the paged KV layout, and
the distributed boundary channel under the forced 8-device CPU mesh
(dp×tp) — the sharded-serving PR's acceptance surface.

Bit-equality pairs share compiled programs where the arms differ only
host-side (pipeline depth), and every engine here runs the tiny f32
toy model: the kv8 family's sharded sandwich routes through shard_map
islands this container's jax cannot build (a pre-existing env
limitation covered by the quantized MULTICHIP dryrun legs), while the
f32 family exercises the identical carry/donation/pipeline machinery.
"""

import functools
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import DecodeEngine, NotCoordinator
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.parallel.distributed import BoundaryChannel, ChannelClosed
from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh, set_current_mesh
from mlcomp_tpu.train.state import init_model

# compiled-program pool (conftest's shared idiom): every engine of
# the same (mesh-ness, layout) config shares one set of jitted
# programs — depth is host-side, so d1/d2 arms compile once
from conftest import (
    close_pooled_engine as _close,
    share_engine_fns as _share,
)


@functools.lru_cache(maxsize=None)
def _model_and_params(seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


@functools.lru_cache(maxsize=None)
def _mesh():
    return make_mesh(MeshSpec.from_config({"dp": 4, "tp": 2}))


def _reference(model, params, ids, n_new, bucket=16):
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask),
    )
    return np.asarray(out)[0, bucket:].tolist()


def _mixed_workload(model, params, depth, kv_layout, eos_c):
    """Mid-stream admission + EOS-mid-dispatch workload on a sharded
    engine: A streams while B joins (slots full → C queues and joins
    mid-stream), C stops at an EOS landing inside a K=2 dispatch."""
    mesh = _mesh()
    set_current_mesh(mesh)
    rs = np.random.RandomState(11)
    ids_a = rs.randint(1, 64, 5).tolist()
    ids_b = rs.randint(1, 64, 7).tolist()
    ids_c = rs.randint(1, 64, 3).tolist()
    eng = _share(
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=10,
                     steps_per_dispatch=2, pipeline_depth=depth,
                     kv_layout=kv_layout, mesh=mesh),
        ("sharded", kv_layout),
    )
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit(ids_a, 9, logprobs=True, stream=qa)
        qa.get(timeout=300)                    # A is decoding
        fb = eng.submit(ids_b, 7)
        fc = eng.submit(ids_c, 6, eos_id=eos_c)  # queues: slots full
        ra = fa.result(timeout=300)
        rb = fb.result(timeout=300)
        rc = fc.result(timeout=300)
        st = eng.stats()
        assert st["pipeline"]["depth"] == depth
        if depth > 1:
            assert st["pipeline"]["peak_inflight"] >= 2
    finally:
        _close(eng)
    return {"a": (ra["ids"], ra["logprobs"]), "b": rb["ids"],
            "c": rc["ids"]}


def test_sharded_depth2_bit_identical_to_depth1_and_paged_to_dense():
    """The acceptance equalities, in one compiled workload: under the
    8-device dp×tp mesh a depth-2 pipelined engine emits tokens (and
    logprobs) bit-identical to depth-1, the sharded PAGED layout
    matches sharded dense bit-exact, and all of them match bare
    generate — with a mid-stream admission and an EOS mid-dispatch in
    the mix."""
    model, params = _model_and_params()
    rs = np.random.RandomState(11)
    ids_a = rs.randint(1, 64, 5).tolist()
    rs.randint(1, 64, 7)
    ids_c = rs.randint(1, 64, 3).tolist()
    eos_c = _reference(model, params, ids_c, 1)[0]
    d1 = _mixed_workload(model, params, 1, "dense", eos_c)
    d2 = _mixed_workload(model, params, 2, "dense", eos_c)
    p2 = _mixed_workload(model, params, 2, "paged", eos_c)
    assert d1 == d2, (d1, d2)
    assert p2 == d2, (p2, d2)
    assert d1["a"][0] == _reference(model, params, ids_a, 9)
    assert d1["c"] == [eos_c]                  # EOS stopped it at one


def test_mesh_defaults_pipelined_and_remaining_rejections_name_followup():
    """Engine(..., mesh=...) no longer rejects pipeline_depth=2 or
    kv_layout='paged'; the default depth under a mesh is 2; the
    REMAINING incompatibilities (spec, prefix cache, forced pallas
    knobs) are rejected with messages naming the follow-up."""
    model, params = _model_and_params()
    kw = dict(slots=2, prompt_buckets=(16,), max_new_cap=8)

    class FakeMesh:  # construction-time checks precede any mesh use
        pass

    eng = DecodeEngine(model, {"params": params}, mesh=FakeMesh(), **kw)
    try:
        assert eng.pipeline_depth == 2  # mesh default: pipelined too
    finally:
        eng.close()
    eng = DecodeEngine(model, {"params": params}, mesh=FakeMesh(),
                       pipeline_depth=2, **kw)
    try:
        assert eng.pipeline_depth == 2  # explicit depth accepted
    finally:
        eng.close()
    with pytest.raises(ValueError, match="follow-up"):
        DecodeEngine(model, {"params": params}, mesh=FakeMesh(),
                     spec_k=2, **kw)
    with pytest.raises(ValueError, match="follow-up"):
        import os

        os.environ["MLCOMP_TPU_PAGED_ATTN"] = "pallas"
        try:
            DecodeEngine(model, {"params": params}, mesh=FakeMesh(),
                         kv_layout="paged", **kw)
        finally:
            os.environ.pop("MLCOMP_TPU_PAGED_ATTN", None)
    with pytest.raises(ValueError, match="follow-up"):
        import os

        os.environ["MLCOMP_TPU_PAGE_GATHER"] = "pallas"
        try:
            DecodeEngine(model, {"params": params}, mesh=FakeMesh(),
                         kv_layout="paged", **kw)
        finally:
            os.environ.pop("MLCOMP_TPU_PAGE_GATHER", None)


def test_donation_sharding_round_trip():
    """The donated sharded carry keeps its shardings through the
    dispatch chain: page arrays are BORN tp-sharded at the kv-head
    axis (tables replicated) and hold exactly that sharding after
    admissions, dispatches, retirements, and lazy page growth — the
    runtime half of graftcheck's donation-sharding rule."""
    from jax.sharding import PartitionSpec as P

    model, params = _model_and_params()
    mesh = _mesh()
    set_current_mesh(mesh)
    eng = _share(
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=10,
                     steps_per_dispatch=2, pipeline_depth=2,
                     kv_layout="paged", mesh=mesh),
        ("sharded", "paged"),
    )
    try:
        from jax.sharding import NamedSharding

        mesh_ = eng.mesh
        pages = eng._dstate["pages"]
        born = [p.sharding for p in pages]
        # cached_key pages: (P, T, Hkv, dh) — heads at axis 2, tp=2
        # divides Hkv=2, so the spec pins tp there
        assert born[0].is_equivalent_to(
            NamedSharding(mesh_, P(None, None, "tp")), pages[0].ndim
        ), born[0].spec
        assert eng._dstate["table"].sharding.is_equivalent_to(
            NamedSharding(mesh_, P()), 2
        )
        eng.submit([3, 14, 15, 9, 2], 8).result(timeout=300)
        eng.submit([7, 3, 44], 8).result(timeout=300)
        after = [p.sharding for p in eng._dstate["pages"]]
        assert all(
            a.is_equivalent_to(b, p.ndim)
            for a, b, p in zip(after, born, eng._dstate["pages"])
        ), [(a.spec, b.spec) for a, b in zip(after, born)]
        assert eng._dstate["table"].sharding.is_equivalent_to(
            NamedSharding(mesh_, P()), 2
        )
    finally:
        _close(eng)


# ---------------------------------------------------- boundary channel


def test_boundary_channel_framing_and_close():
    """The TCP broadcast channel in isolation: records arrive in
    order, close() unblocks a waiting recv with ChannelClosed, and a
    single-process channel is inert."""
    from mlcomp_tpu.scheduler.worker import _free_port

    inert = BoundaryChannel(num_processes=1, process_id=0)
    assert inert.is_coordinator
    inert.send({"k": 1})   # no-op, no sockets
    inert.close()

    port = _free_port()
    follower_box: dict = {}

    def follow():
        ch = BoundaryChannel(num_processes=2, process_id=1,
                             address="127.0.0.1:0", port=port)
        follower_box["ch"] = ch
        follower_box["recs"] = [ch.recv(), ch.recv()]
        try:
            ch.recv()
        except ChannelClosed:
            follower_box["closed"] = True

    t = threading.Thread(target=follow, daemon=True)
    t.start()
    coord = BoundaryChannel(num_processes=2, process_id=0, port=port)
    coord.send({"new": [], "k": 2})
    coord.send({"new": [{"rid": 7}], "retired": [[7, "cancelled"]]})
    time.sleep(0.2)
    coord.close()
    t.join(timeout=10)
    assert not t.is_alive()
    assert follower_box["recs"][0]["k"] == 2
    assert follower_box["recs"][1]["retired"] == [[7, "cancelled"]]
    assert follower_box.get("closed") is True
    follower_box["ch"].close()


def test_single_process_gang_follower_replays_coordinator():
    """A REAL coordinator/follower pair over localhost TCP in one
    process (no jax.distributed needed): the follower engine replays
    the coordinator's broadcast boundaries — same admissions, same
    dispatch count, same emitted tokens, cancel retirements included —
    and its submit surface is closed (NotCoordinator).  The stop
    record ends the follower's loop when the coordinator closes."""
    from mlcomp_tpu.scheduler.worker import _free_port

    model, params = _model_and_params()
    mesh = _mesh()
    set_current_mesh(mesh)
    port = _free_port()
    box: dict = {}

    def connect_follower():
        box["chf"] = BoundaryChannel(num_processes=2, process_id=1,
                                     address="127.0.0.1:0", port=port)

    t = threading.Thread(target=connect_follower, daemon=True)
    t.start()
    chc = BoundaryChannel(num_processes=2, process_id=0, port=port)
    t.join(timeout=10)
    chf = box["chf"]
    kw = dict(slots=2, prompt_buckets=(16,), max_new_cap=10,
              steps_per_dispatch=2, pipeline_depth=2, mesh=mesh)
    eng_c = _share(
        DecodeEngine(model, {"params": params}, dist=chc, **kw),
        ("gang",),
    )
    eng_f = _share(
        DecodeEngine(model, {"params": params}, dist=chf, **kw),
        ("gang",),
    )
    try:
        assert eng_c.is_coordinator and not eng_f.is_coordinator
        with pytest.raises(NotCoordinator):
            eng_f.submit([1, 2, 3], 4)
        r1 = eng_c.submit([3, 14, 15, 9, 2], 6).result(timeout=300)
        assert r1["ids"] == _reference(model, params,
                                       [3, 14, 15, 9, 2], 6)
        # a cancel retirement rides the broadcast too
        qs: "queue.Queue" = queue.Queue()
        f2 = eng_c.submit([7, 3, 44], 10, stream=qs)
        qs.get(timeout=300)                   # decoding
        assert eng_c.cancel(f2.rid)
        with pytest.raises(Exception):
            f2.result(timeout=300)
        deadline = time.time() + 120
        while time.time() < deadline:
            stf = eng_f.stats()
            if (stf["emitted_tokens"] == eng_c.stats()["emitted_tokens"]
                    and stf["cancelled"] == 1):
                break
            time.sleep(0.05)
        stf = eng_f.stats()
        stc = eng_c.stats()
        assert stf["emitted_tokens"] == stc["emitted_tokens"]
        assert stf["prefills"] == stc["prefills"] == 2
        assert stf["cancelled"] == 1
        assert stc["mesh"]["coordinator"] is True
        assert stf["mesh"]["coordinator"] is False
    finally:
        # coordinator first: its loop's finally broadcasts the stop
        # record that ends the follower's loop
        _close(eng_c)
        eng_f._thread.join(timeout=60)
        alive = eng_f._thread.is_alive()
        _close(eng_f)
        assert not alive  # the stop record ended the follower loop


@pytest.mark.slow
def test_two_process_distributed_serve_gang(tmp_path):
    """The real multi-host path: 2 jax.distributed processes × 4
    virtual CPU devices serve one SPMD gang — process 0 fronts, the
    follower replays, tokens match a single-host reference.  Slow:
    spawns fresh JAX processes; skipped (not failed) where the CPU
    backend cannot run multi-process computations (this container's
    jax — the driver environment can)."""
    import json
    import os
    import subprocess
    import sys

    from mlcomp_tpu.scheduler.worker import _free_port
    from mlcomp_tpu.serve import load_service

    cfg = {"name": "transformer_lm", "vocab_size": 64, "hidden": 64,
           "layers": 2, "heads": 4, "mlp_dim": 128, "dtype": "float32"}
    ref = load_service(cfg, batch_sizes=(2,), prompt_buckets=(16,),
                       max_new_buckets=(8,), metrics_history_interval=0)
    try:
        e1 = ref.generate([3, 14, 15, 9, 2], 6)["ids"]
        e2 = ref.generate([7, 3, 44], 6)["ids"]
    finally:
        ref.close()

    child = tmp_path / "gang_child.py"
    child.write_text(
        "import json, os\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from mlcomp_tpu.parallel.distributed import ("
        "BoundaryChannel, init_distributed)\n"
        "init_distributed()\n"
        "ch = BoundaryChannel()\n"
        "from mlcomp_tpu.serve import load_service\n"
        f"cfg = {cfg!r}\n"
        "svc = load_service(cfg, mesh_cfg={'dp': 2, 'tp': 4},\n"
        "    batch_sizes=(2,), prompt_buckets=(16,),\n"
        "    max_new_buckets=(8,), metrics_history_interval=0,\n"
        "    dist=ch)\n"
        "pid = int(os.environ['MLCOMP_TPU_PROCESS_ID'])\n"
        "try:\n"
        "    svc.warmup()\n"
        "    if pid == 0:\n"
        "        r1 = svc.submit([3, 14, 15, 9, 2], 6).result(300)\n"
        "        r2 = svc.submit([7, 3, 44], 6).result(300)\n"
        "        want = json.loads(os.environ['GANG_EXPECTED'])\n"
        "        assert [r1['ids'], r2['ids']] == want, (r1, r2, want)\n"
        "        assert svc.stats()['ready'] is True\n"
        "    else:\n"
        "        assert svc.stats()['ready'] is False\n"
        "        svc.engine._thread.join(timeout=300)\n"
        "        assert svc.engine.stats()['dispatches'] >= 3\n"
        "finally:\n"
        "    svc.close()\n"
        "print('gang proc', pid, 'ok', flush=True)\n"
    )
    port, sync_port = _free_port(), _free_port()
    env_base = {
        k: v for k, v in os.environ.items()
        if "MLCOMP" not in k and k not in ("XLA_FLAGS",)
    }
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env_base["MLCOMP_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    env_base["MLCOMP_TPU_NUM_PROCESSES"] = "2"
    env_base["MLCOMP_TPU_SYNC_PORT"] = str(sync_port)
    env_base["GANG_EXPECTED"] = json.dumps([e1, e2])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env_base["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, os.environ.get("PYTHONPATH")) if p
    )
    procs = []
    for pid in range(2):
        env = dict(env_base, MLCOMP_TPU_PROCESS_ID=str(pid))
        procs.append(subprocess.Popen(
            [sys.executable, str(child)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    blob = "\n".join(outs)
    if "Multiprocess computations aren't implemented" in blob:
        pytest.skip("CPU backend cannot run multi-process computations "
                    "in this jax build (pre-existing env limitation)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"gang process {pid} exited {p.returncode}:\n{out[-3000:]}"
        )

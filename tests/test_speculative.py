"""Speculative decoding (models/speculative.py): greedy-exactness vs
``generate`` across KV/weight modes, the n-gram proposer, eos and
budget handling, and acceptance accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.models.speculative import ngram_propose, speculative_generate
from mlcomp_tpu.train.state import init_model


def _lm(**kw):
    cfg = {
        "name": "transformer_lm", "vocab_size": 96, "hidden": 128,
        "layers": 2, "heads": 2, "mlp_dim": 256, "dtype": "float32",
    }
    cfg.update(kw)
    return create_model(cfg)


def _vars(model, s=8, seed=0):
    prompt = jnp.ones((1, s), jnp.int32)
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return {"params": params}


def test_ngram_propose_lookup_and_fallback():
    ids = jnp.asarray([5, 7, 9, 3, 5, 7, 2, 4, 0, 0], jnp.int32)
    # real tokens = ids[:6] = [5,7,9,3,5,7]; context bigram = (prev=7,
    # tok0=9), which occurred at p=1 -> propose what followed: [3, 5, 7]
    prop = ngram_propose(ids, jnp.int32(6), jnp.int32(9), 3)
    np.testing.assert_array_equal(np.asarray(prop), [3, 5, 7])
    # no such bigram anywhere: all-pad proposal
    prop2 = ngram_propose(ids, jnp.int32(6), jnp.int32(77), 3, pad_id=0)
    np.testing.assert_array_equal(np.asarray(prop2), [0, 0, 0])
    # bigram (7, 2) occurs at p=5 but its continuation starts at
    # p+2=7 >= cur... with cur=7 the continuation [4, pad...] clips:
    # in-past source token kept, past-cur tail masked to pad
    prop3 = ngram_propose(ids, jnp.int32(8), jnp.int32(2), 4)
    # cur=8: real = [5,7,9,3,5,7,2,4]; prev=ids[7]=4, tok0=2: bigram
    # (4, 2) never occurs -> pads
    np.testing.assert_array_equal(np.asarray(prop3), [0, 0, 0, 0])


@pytest.mark.parametrize("kv_quant", [False, True])
def test_speculative_matches_generate_greedy(kv_quant):
    model = _lm(kv_quant=kv_quant)
    variables = _vars(model)
    rs = np.random.RandomState(2)
    for trial in range(3):
        prompt = jnp.asarray(rs.randint(1, 96, (1, 8)))
        ref = generate(model, variables, prompt, 12)
        out = speculative_generate(
            model, variables, prompt, 12, spec_k=4
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(ref),
            err_msg=f"kv_quant={kv_quant} trial={trial}",
        )


def test_speculative_matches_generate_int8_kernel():
    from mlcomp_tpu.ops.quant import quantize_params

    model = _lm(hidden=256, mlp_dim=512, vocab_size=128)
    prompt = jnp.asarray(np.random.RandomState(3).randint(1, 128, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    ref = generate(model, q, prompt, 10, quant_kernel=True)
    out = speculative_generate(
        model, q, prompt, 10, spec_k=3, quant_kernel=True
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_budget_smaller_than_k():
    model = _lm()
    variables = _vars(model)
    prompt = jnp.asarray(np.random.RandomState(5).randint(1, 96, (1, 6)))
    ref = generate(model, variables, prompt, 2)
    out = speculative_generate(model, variables, prompt, 2, spec_k=6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert out.shape == (1, 8)


def test_speculative_eos_matches_generate():
    model = _lm()
    variables = _vars(model)
    prompt = jnp.asarray(np.random.RandomState(7).randint(1, 96, (1, 6)))
    free = np.asarray(generate(model, variables, prompt, 12))[0, 6:]
    eos = int(free[4])  # force an eos hit mid-stream
    ref = generate(model, variables, prompt, 12, eos_id=eos)
    out = speculative_generate(
        model, variables, prompt, 12, spec_k=4, eos_id=eos
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_stats_and_acceptance_on_repetitive_text():
    """Acceptance accounting: steps/emitted come back; a greedy loop
    that settles into a cycle (typical for random weights) must yield
    tokens-per-forward >= 1 and the repetitive structure should let the
    bigram draft accept SOMETHING across trials."""
    model = _lm()
    variables = _vars(model)
    prompt = jnp.asarray(
        np.tile(np.asarray([11, 23, 42, 11, 23, 42, 11, 23], np.int32),
                (1, 1))
    )
    out, stats = speculative_generate(
        model, variables, prompt, 24, spec_k=4, with_stats=True
    )
    emitted, steps = int(stats["emitted"]), int(stats["steps"])
    assert emitted == 24
    assert 1 <= steps <= emitted
    ref = generate(model, variables, prompt, 24)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_prompt_mask_matches_generate():
    """The LEFT-pad serving bucket contract: a padded prompt + mask
    produces the same generated tail as both generate-with-mask and
    the unpadded speculative run."""
    model = _lm()
    variables = _vars(model)
    rs = np.random.RandomState(11)
    real = rs.randint(1, 96, 5)
    s_bucket = 12
    row = np.zeros(s_bucket, np.int64)
    row[-5:] = real
    mask = np.zeros(s_bucket, bool)
    mask[-5:] = True
    prompt = jnp.asarray(row[None])
    pm = jnp.asarray(mask[None])
    ref = generate(model, variables, prompt, 10, prompt_mask=pm)
    out = speculative_generate(
        model, variables, prompt, 10, spec_k=4, prompt_mask=pm
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    out2 = speculative_generate(
        model, variables, jnp.asarray(real[None]), 10, spec_k=4
    )
    np.testing.assert_array_equal(
        np.asarray(out)[:, s_bucket:], np.asarray(out2)[:, 5:]
    )


def test_speculative_rejects_batches_and_bad_args():
    model = _lm()
    variables = _vars(model)
    with pytest.raises(ValueError, match="single-sequence"):
        speculative_generate(
            model, variables, jnp.ones((2, 4), jnp.int32), 4
        )
    with pytest.raises(ValueError, match="spec_k"):
        speculative_generate(
            model, variables, jnp.ones((1, 4), jnp.int32), 4, spec_k=0
        )


def test_speculative_1d_prompt_and_jit():
    """(S,) prompts are accepted, and the whole function jits (the
    production wrapper) with identical output."""
    model = _lm()
    variables = _vars(model)
    prompt = jnp.asarray(np.random.RandomState(9).randint(1, 96, (6,)))
    out = speculative_generate(model, variables, prompt, 8, spec_k=3)
    jitted = jax.jit(
        lambda v, p: speculative_generate(model, v, p, 8, spec_k=3)
    )
    out2 = jitted(variables, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    ref = generate(model, variables, prompt[None], 8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

import pytest

from mlcomp_tpu.dag import parse_dag, topo_sort, ready_tasks
from mlcomp_tpu.dag.graph import DagValidationError, doomed_tasks
from mlcomp_tpu.dag.parser import expand_grid
from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.utils.config import ConfigError

SIMPLE = """
info: {name: demo, project: tests}
executors:
  prep:
    type: preprocess
  train:
    type: train
    stage: train
    depends: prep
    resources: {chips: 8}
    args: {epochs: 2}
  infer:
    type: infer
    stage: infer
    depends: train
"""


def test_parse_simple():
    dag = parse_dag(SIMPLE)
    assert dag.name == "demo" and dag.project == "tests"
    assert dag.task_names == ["prep", "train", "infer"]
    t = dag.task("train")
    assert t.depends == ("prep",)
    assert t.resources.chips == 8
    assert t.args == {"epochs": 2}
    assert t.stage == "train"


def test_topo_order():
    dag = parse_dag(SIMPLE)
    order = [t.name for t in topo_sort(dag.tasks)]
    assert order.index("prep") < order.index("train") < order.index("infer")


def test_cycle_detected():
    bad = """
info: {name: cyc}
executors:
  a: {type: x, depends: b}
  b: {type: x, depends: a}
"""
    with pytest.raises(DagValidationError):
        parse_dag(bad)


def test_unknown_dep():
    bad = """
info: {name: bad}
executors:
  a: {type: x, depends: ghost}
"""
    with pytest.raises(ConfigError):
        parse_dag(bad)


def test_grid_expansion():
    grid_yaml = """
info: {name: grid}
executors:
  train:
    type: train
    grid:
      lr: [0.1, 0.01]
      model.width: [64, 128]
    args: {model: {depth: 3}, epochs: 1}
  report:
    type: submit
    depends: train
"""
    dag = parse_dag(grid_yaml)
    train_tasks = [t for t in dag.tasks if t.name.startswith("train")]
    assert len(train_tasks) == 4
    assert train_tasks[0].name == "train[0]"
    # grid params override nested args, base args preserved
    assert train_tasks[0].args == {"model": {"depth": 3, "width": 64}, "epochs": 1, "lr": 0.1}
    assert train_tasks[3].args["lr"] == 0.01
    assert train_tasks[3].args["model"]["width"] == 128
    # fan-in join
    report = dag.task("report")
    assert report.depends == ("train[0]", "train[1]", "train[2]", "train[3]")


def test_expand_grid_no_grid():
    assert expand_grid("t", {}, {"a": 1}) == [("t", {"a": 1}, ())]


def test_ready_and_doomed():
    dag = parse_dag(SIMPLE)
    st = {n: TaskStatus.NOT_RAN for n in dag.task_names}
    ready = ready_tasks(dag.tasks, st)
    assert [t.name for t in ready] == ["prep"]
    st["prep"] = TaskStatus.SUCCESS
    assert [t.name for t in ready_tasks(dag.tasks, st)] == ["train"]
    st["train"] = TaskStatus.FAILED
    assert doomed_tasks(dag.tasks, st) == {"infer"}

"""Static cross-checks on the dashboard's embedded JS (report/server.py).

The image has no browser or JS engine (round-5 session: no chrome/node/
bun/quickjs — the WebBrowser attempt failed to spawn), so the ~250
lines of chart/DAG/action script cannot EXECUTE here.  These tests
close the likeliest silent-breakage classes statically instead:

- every ``getElementById`` target exists in the HTML;
- every ``/api/...`` URL the JS fetches resolves against the server's
  actual route tables (GET and POST), with representative ids/names
  substituted for the template variables;
- the JSON keys the JS destructures off each endpoint exist in real
  responses served from a seeded store (tools/demo_store.py — the same
  store a human points a browser at);
- the script is at least brace/paren/backtick balanced outside string
  literals (a truncated paste or an unclosed template literal would
  kill the whole dashboard).

A human with a browser verifies pixels via::

    python tools/demo_store.py /tmp/demo.db
    python -m mlcomp_tpu.cli report --db /tmp/demo.db --port 8765
"""

from __future__ import annotations

import json
import re
import urllib.request

import pytest

from mlcomp_tpu.report.server import _DASHBOARD, _POST_ROUTES, _ROUTES


def _script() -> str:
    m = re.search(r"<script>(.*)</script>", _DASHBOARD, re.S)
    assert m, "dashboard has no script block"
    return m.group(1)


def test_every_js_element_id_exists_in_html():
    script = _script()
    html = _DASHBOARD[: _DASHBOARD.index("<script>")]
    ids = set(re.findall(r"getElementById\('([\w-]+)'\)", script))
    assert ids, "no getElementById calls found — extraction broken?"
    declared = set(re.findall(r'id="([\w-]+)"', html))
    missing = ids - declared
    assert not missing, f"JS references undeclared element ids: {missing}"


def test_every_fetched_api_path_routes():
    """Substitute representative values for the JS template variables,
    then require every fetched URL to match a server route."""
    script = _script()
    # literal and template-concatenated API strings:  '/api/x/'+v+'/y'
    calls = re.findall(r"'(/api/[^']*)'((?:\s*\+\s*[\w.\[\]]+\s*"
                       r"(?:\+\s*'[^']*')?)*)", script)
    assert calls, "no /api fetches found in dashboard JS"
    get_routes = [rx for rx, _ in _ROUTES]
    post_routes = [rx for rx, _ in _POST_ROUTES]

    # rebuild each fetch expression, substituting representative values
    # by variable name: action verbs are 'stop'/'restart', metric names
    # can carry slashes, everything else is an id
    subs = {"verb": "stop", "sel.value": "train/loss", "m": "train/loss",
            "n": "train/loss"}
    exprs = set()
    for lead, tail in calls:
        url = lead
        for lit, var in re.findall(r"\+\s*(?:'([^']*)'|([\w.\[\]]+))", tail):
            url += lit if lit else subs.get(var, "7")
        exprs.add(url)
    unmatched = [
        url for url in exprs
        if not any(rx.match(url) for rx in get_routes + post_routes)
    ]
    assert not unmatched, f"dashboard fetches unrouted paths: {unmatched}"


def test_script_brackets_balanced():
    script = _script()
    # strip string literals (',",`) and comments, then count brackets
    stripped = re.sub(
        r"'(?:\\.|[^'\\])*'|\"(?:\\.|[^\"\\])*\"|`(?:\\.|[^`\\])*`"
        r"|//[^\n]*",
        "", script)
    for open_c, close_c in ("{}", "()", "[]"):
        assert stripped.count(open_c) == stripped.count(close_c), (
            f"unbalanced {open_c}{close_c} in dashboard script"
        )
    assert script.count("`") % 2 == 0, "unclosed template literal"


@pytest.fixture()
def demo_server(tmp_path):
    from mlcomp_tpu.report.server import start_in_thread
    from tools.demo_store import seed

    db = str(tmp_path / "demo.db")
    seed(db)
    srv, port = start_in_thread(db, port=0)
    try:
        yield port
    finally:
        srv.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read())


def test_js_consumed_keys_exist_in_seeded_responses(demo_server):
    """The seeded demo store (what a human browses) serves every field
    the JS destructures: dags table columns, task columns, worker info,
    report payload fields for both renderers, and the layout artifact."""
    port = demo_server
    dags = _get(port, "/api/dags")
    assert {"id", "name", "project", "status", "counts"} <= set(dags[0])
    in_flight = [d for d in dags if d["status"] == "in_progress"]
    assert in_flight, "demo store must include an in-flight dag (actions)"

    tasks = _get(port, f"/api/dags/{dags[0]['id']}/tasks")
    need = {"id", "name", "executor", "stage", "status", "worker",
            "error", "depends"}
    assert need <= set(tasks[0])
    assert any(t["status"] == "failed" and t["error"] for t in tasks)
    # drawGraph JSON-parses depends and walks names
    names = {t["name"] for t in tasks}
    for t in tasks:
        for dep in json.loads(t["depends"] or "[]"):
            assert dep in names

    # compare dropdown: dag-wide metric names + per-task series
    mnames = _get(port, f"/api/dags/{dags[0]['id']}/metrics")
    assert "train/loss" in mnames
    by_task = _get(port, f"/api/dags/{dags[0]['id']}/metrics/train/loss")
    assert by_task and all(
        len(p) == 2 for s in by_task.values() for p in s
    )

    workers = _get(port, "/api/workers")
    assert {"name", "chips", "busy_chips", "status", "heartbeat",
            "info"} <= set(workers[0])
    infos = [json.loads(w["info"]) for w in workers if w["info"]]
    assert any({"load1", "mem_free_gb", "tasks"} <= set(i) for i in infos)
    assert any(w["status"] == "dead" for w in workers)

    # report payloads for both renderers + the layout artifact
    seen_kinds = set()
    for t in tasks:
        for rep in _get(port, f"/api/tasks/{t['id']}/reports"):
            p = _get(port, f"/api/reports/{rep['id']}")
            seen_kinds.add(p.get("kind"))
            if p.get("kind") == "classification":
                assert {"accuracy", "mean_average_precision", "n",
                        "pr_curves", "average_precision", "per_class",
                        "confusion", "class_names", "worst"} <= set(p)
            elif p.get("kind") == "segmentation":
                assert {"pixel_accuracy", "mean_iou", "mean_dice",
                        "n_pixels", "per_class", "confusion",
                        "class_names"} <= set(p)
            elif p.get("kind") == "layout":
                assert all("type" in panel for panel in p["panels"])
    assert {"classification", "segmentation", "layout"} <= seen_kinds

    logs = _get(port, f"/api/tasks/{tasks[0]['id']}/logs")
    if logs:
        assert {"level", "message"} <= set(logs[0])

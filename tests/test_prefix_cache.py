"""Prefix KV cache (mlcomp_tpu/cache): trie semantics (longest-prefix
match, LRU eviction, ref-count pinning, edge splits), end-to-end
engine equality — cache-hit generation must emit EXACTLY the tokens
cold prefill emits, bf16 and kv8 cache layouts — and the serving
surface (per-request cache_hit_tokens, /cache/stats, warmup
isolation)."""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.cache import PrefixIndex, PrefixKVCache
from mlcomp_tpu.cache.kv_store import KVBlock
from mlcomp_tpu.engine import DecodeEngine
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import GenerationService
from mlcomp_tpu.train.state import init_model


def _block(ids):
    """Self-checking block: the payload IS the ids, so slice/split
    bookkeeping errors surface as token mismatches."""
    return KVBlock(
        {"ids": np.asarray(list(ids), np.int64)[None]}, {"ids": 1},
        len(ids),
    )


def _lease_ids(lease):
    out = []
    for block, take in lease.segments:
        out.extend(block.arrays["ids"][0, :take].tolist())
    return out


# ----------------------------------------------------------- trie unit


def test_trie_longest_prefix_match_and_split():
    idx = PrefixIndex(1 << 20)
    assert idx.lookup([1, 2, 3]) is None
    idx.insert([1, 2, 3, 4], _block([1, 2, 3, 4]))
    with idx.lookup([1, 2, 3, 9]) as lease:
        assert lease.tokens == 3 and _lease_ids(lease) == [1, 2, 3]
    # divergence mid-edge splits the node; both arms stay reachable
    idx.insert([1, 2, 7, 8], _block([1, 2, 7, 8]))
    idx.check_invariants()
    with idx.lookup([1, 2, 7, 8, 5]) as lease:
        assert lease.tokens == 4 and _lease_ids(lease) == [1, 2, 7, 8]
    with idx.lookup([1, 2, 3, 4]) as lease:
        assert lease.tokens == 4 and _lease_ids(lease) == [1, 2, 3, 4]
    # dedup: re-inserting an existing prefix stores nothing new
    assert idx.insert([1, 2, 3], _block([1, 2, 3])) == 0
    # offset insert: block covers only the new suffix
    assert idx.insert([1, 2, 3, 4, 5, 6], _block([5, 6]), offset=4) == 2
    with idx.lookup([1, 2, 3, 4, 5, 6]) as lease:
        assert _lease_ids(lease) == [1, 2, 3, 4, 5, 6]


def test_trie_lru_eviction_under_byte_budget():
    # payload int64 -> 8 bytes/token; budget of 7 tokens
    idx = PrefixIndex(7 * 8)
    idx.insert([1, 2, 3], _block([1, 2, 3]))
    idx.insert([5, 6, 7], _block([5, 6, 7]))
    idx.lookup([1, 2, 3]).release()          # [5,6,7] is now LRU
    idx.insert([8, 9], _block([8, 9]))       # 8 tokens > 7 -> evict LRU
    idx.check_invariants()
    st = idx.stats()
    assert st["evictions"] == 1 and st["bytes"] <= 7 * 8
    assert idx.lookup([5, 6, 7]) is None     # the LRU victim
    assert idx.lookup([1, 2, 3]).tokens == 3


def test_trie_refcount_pins_against_eviction():
    idx = PrefixIndex(6 * 8)
    idx.insert([1, 2, 3], _block([1, 2, 3]))
    lease = idx.lookup([1, 2, 3])
    # massive pressure: everything unpinned must go before the lease's
    # nodes; the pinned data stays intact even while over budget
    idx.insert([7] * 6, _block([7] * 6))
    idx.check_invariants()
    assert _lease_ids(lease) == [1, 2, 3]
    with idx.lookup([1, 2, 3]) as again:
        assert again.tokens == 3
    lease.release()
    lease.release()  # idempotent
    idx.evict_to_budget()
    assert idx.stats()["pinned_nodes"] == 0
    assert idx.stats()["bytes"] <= 6 * 8


def test_trie_concurrent_eviction_race():
    """Racing lookups/inserts/evictions under a tiny budget: pinned
    leases keep their bytes, invariants hold throughout, refcounts
    return to zero."""
    idx = PrefixIndex(40 * 8)
    errs = []

    def worker(seed):
        rs = np.random.RandomState(seed)
        try:
            for _ in range(200):
                ids = rs.randint(1, 5, rs.randint(1, 12)).tolist()
                if rs.rand() < 0.5:
                    idx.insert(ids, _block(ids))
                else:
                    lease = idx.lookup(ids)
                    if lease is not None:
                        want = ids[: lease.tokens]
                        idx.evict_to_budget()  # pressure WHILE pinned
                        assert _lease_ids(lease) == want
                        lease.release()
                idx.check_invariants()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]
    idx.check_invariants()
    assert idx.stats()["pinned_nodes"] == 0
    idx.evict_to_budget()
    assert idx.stats()["bytes"] <= 40 * 8


# ------------------------------------------------------- engine e2e


def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


def _reference(model, params, ids, n_new, bucket=32):
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask),
    )
    return np.asarray(out)[0, bucket:].tolist()


@pytest.mark.parametrize("kv_quant", [False, True])
def test_engine_cache_hit_outputs_equal_cold(kv_quant):
    """The acceptance bar: token-level output equality between
    cache-hit and uncached generation, for both cache layouts —
    identical resubmit, shared-prefix different-suffix, and a
    different-LENGTH sharer (different left-pad offset)."""
    model, params = _model_and_params(kv_quant)
    eng = DecodeEngine(
        model, {"params": params}, slots=2, prompt_buckets=(32,),
        max_new_cap=8, prefill_chunk=8,
        prefix_cache=PrefixKVCache(max_bytes=64 << 20),
    )
    try:
        rs = np.random.RandomState(5)
        ids = rs.randint(1, 64, 28).tolist()
        cold = eng.submit(ids, 6).result(timeout=300)
        assert cold["cache_hit_tokens"] == 0
        eng.prefix_cache.flush()  # captures land on a background worker
        hot = eng.submit(ids, 6).result(timeout=300)
        # 28 real tokens, pad 4, chunk 8: match capped at 27 ->
        # boundary chunk 3 -> 3*8-4 = 20 tokens skipped
        assert hot["cache_hit_tokens"] == 20
        assert hot["ids"] == cold["ids"] == _reference(
            model, params, ids, 6
        )
        # shared 20-token prefix, fresh suffix, same length
        ids2 = ids[:20] + rs.randint(1, 64, 8).tolist()
        r2 = eng.submit(ids2, 6).result(timeout=300)
        assert r2["cache_hit_tokens"] > 0
        assert r2["ids"] == _reference(model, params, ids2, 6)
        # different length (start_pad 8 vs 4): rows transplant by token
        # index, not slot
        ids3 = ids[:20] + rs.randint(1, 64, 4).tolist()
        r3 = eng.submit(ids3, 6).result(timeout=300)
        assert r3["cache_hit_tokens"] > 0
        assert r3["ids"] == _reference(model, params, ids3, 6)
        eng.prefix_cache.flush()
        st = eng.stats()["prefix_cache"]
        assert st["hits"] == 3 and st["misses"] == 1
        assert st["used_hit_tokens"] > 0 and st["bytes"] > 0
    finally:
        eng.close()


def test_engine_cache_budget_eviction_keeps_serving():
    """A budget too small for the traffic evicts instead of growing —
    and requests keep producing exact outputs (hit or miss)."""
    model, params = _model_and_params()
    # room for roughly one 28-token prompt's rows (~57 KB), not several
    eng = DecodeEngine(
        model, {"params": params}, slots=2, prompt_buckets=(32,),
        max_new_cap=8, prefill_chunk=8,
        prefix_cache=PrefixKVCache(max_bytes=60_000),
    )
    try:
        rs = np.random.RandomState(6)
        for _ in range(4):
            ids = rs.randint(1, 64, 28).tolist()
            got = eng.submit(ids, 4).result(timeout=300)
            assert got["ids"] == _reference(model, params, ids, 4)
            eng.prefix_cache.flush()
        st = eng.stats()["prefix_cache"]
        assert st["evictions"] > 0
        assert st["bytes"] <= 60_000
    finally:
        eng.close()


def test_engine_warns_when_no_bucket_can_hit():
    """Hits are chunk-granular: a bucket that prefills as one chunk
    can never hit — the constructor says so instead of serving a
    silently zero-hit cache."""
    import warnings

    model, params = _model_and_params()
    with pytest.warns(UserWarning, match="impossible"):
        eng = DecodeEngine(
            model, {"params": params}, slots=2, prompt_buckets=(32,),
            max_new_cap=8,  # default prefill_chunk 256 > bucket 32
            prefix_cache=PrefixKVCache(max_bytes=1 << 20),
        )
    eng.close()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # divisible buckets stay silent
        eng = DecodeEngine(
            model, {"params": params}, slots=2, prompt_buckets=(32,),
            max_new_cap=8, prefill_chunk=8,
            prefix_cache=PrefixKVCache(max_bytes=1 << 20),
        )
    eng.close()


def test_engine_mesh_refuses_prefix_cache():
    model, params = _model_and_params()

    class FakeMesh:  # the check precedes any mesh use
        pass

    with pytest.raises(ValueError, match="single-chip"):
        DecodeEngine(
            model, {"params": params}, slots=2, prompt_buckets=(32,),
            max_new_cap=8, mesh=FakeMesh(),
            prefix_cache=PrefixKVCache(max_bytes=1 << 20),
        )


# ------------------------------------------------------- service/HTTP


def test_service_prefix_cache_http_stats_and_hit_tokens():
    """GenerationService(prefix_cache=True): warmup stays out of the
    cache, responses carry cache_hit_tokens, and GET /cache/stats
    serves the counters (404 when the cache is off)."""
    import json
    import socket
    import urllib.error
    import urllib.request

    from mlcomp_tpu.serve import serve_http

    model, params = _model_and_params()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(32,), max_new_buckets=(4, 8),
        prefill_chunk=8, prefix_cache=True,
        prefix_cache_bytes=64 << 20,
    )
    assert svc.engine is not None and svc.engine.prefix_cache is not None
    svc.warmup()
    assert svc.cache_stats()["inserted_tokens"] == 0  # warmup excluded

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    threading.Thread(
        target=serve_http, args=(svc,), kwargs={"port": port}, daemon=True,
    ).start()

    import time as _t

    ids = np.random.RandomState(2).randint(1, 64, 28).tolist()
    body = json.dumps({"prompt": ids, "max_new_tokens": 4}).encode()

    def post():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    for _ in range(50):
        try:
            cold = post()
            break
        except OSError:
            _t.sleep(0.1)
    else:
        raise AssertionError("server never came up")
    svc.engine.prefix_cache.flush()  # async capture -> deterministic hit
    hot = post()
    assert cold["cache_hit_tokens"] == 0
    assert hot["cache_hit_tokens"] > 0
    assert hot["ids"] == cold["ids"]
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/cache/stats"
    ) as r:
        stats = json.loads(r.read())
    assert stats["hits"] >= 1 and stats["bytes"] > 0
    svc.close()

    # cache off -> /cache/stats is 404 (and cache_stats() is None)
    svc2 = GenerationService(
        model, {"params": params}, batch_sizes=(1,),
        prompt_buckets=(32,), max_new_buckets=(4,),
    )
    assert svc2.cache_stats() is None
    svc2.close()


def test_service_prefix_cache_validation():
    model, params = _model_and_params()
    with pytest.raises(ValueError, match="continuous"):
        GenerationService(
            model, {"params": params}, batcher="window",
            batch_sizes=(1,), prompt_buckets=(32,),
            max_new_buckets=(4,), prefix_cache=True,
        )

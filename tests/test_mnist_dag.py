"""End-to-end: the MNIST classification DAG (BASELINE config #1) runs
through the scheduler with train -> valid -> infer stages."""

import numpy as np

from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.local import run_dag_local


def mnist_dag(tmp_path):
    data = {
        "train": {"name": "synth_mnist", "n": 256, "batch_size": 64},
        "valid": {"name": "synth_mnist", "n": 128, "seed": 1, "batch_size": 64},
    }
    model = {"name": "mnist_cnn", "num_classes": 10, "features": [8, 16], "dense": 32}
    return {
        "info": {"name": "mnist", "project": "examples"},
        "executors": {
            "train": {
                "type": "train",
                "stage": "train",
                "args": {
                    "model": model,
                    "optimizer": {"name": "adam", "lr": 3e-3},
                    "epochs": 2,
                    "data": data,
                    "storage_root": str(tmp_path / "storage"),
                    "project": "examples",
                    "dag_name": "mnist",
                },
            },
            "valid": {
                "type": "valid",
                "stage": "valid",
                "depends": "train",
                "args": {
                    "model": model,
                    "data": {"valid": data["valid"]},
                },
            },
            "infer": {
                "type": "infer",
                "stage": "infer",
                "depends": "train",
                "args": {
                    "model": model,
                    "data": {"infer": {"name": "synth_mnist", "n": 64, "seed": 2, "batch_size": 64}},
                    "out": str(tmp_path / "preds.npz"),
                },
            },
        },
    }


def test_mnist_dag_end_to_end(tmp_db, tmp_path):
    statuses = run_dag_local(
        mnist_dag(tmp_path), db_path=tmp_db, workdir=str(tmp_path)
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values()), statuses

    store = Store(tmp_db)
    rows = {r["name"]: r for r in store.task_rows(1)}
    # train logged loss metrics that decreased
    import json

    train_result = json.loads(rows["train"]["result"])
    assert "ckpt_dir" in train_result
    series = store.metric_series(rows["train"]["id"], "train/loss")
    assert len(series) == 2

    # infer wrote predictions with the right shape
    preds = np.load(tmp_path / "preds.npz")["preds"]
    assert preds.shape == (64, 10)

    # valid logged metrics from the restored checkpoint
    vrow = rows["valid"]
    assert store.metric_series(vrow["id"], "valid/accuracy")

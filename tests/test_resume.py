"""Checkpoint/resume: a restarted train task continues where it stopped.

Simulates the Supervisor requeueing a training task after a worker death:
the second run finds the first run's checkpoint in model storage, restores
the full TrainState (params, optimizer state, step counter) and runs only
the remaining epochs, with epoch numbering continuing — the behavior the
reference gets from Catalyst's resume flag, rebuilt over orbax.
"""

import json

from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.local import run_dag_local


def _dag(tmp_path, epochs):
    return {
        "info": {"name": "resume-demo", "project": "examples"},
        "executors": {
            "train": {
                "type": "train",
                "stage": "train",
                "args": {
                    "model": {
                        "name": "mlp",
                        "hidden": [8],
                        "num_classes": 4,
                    },
                    "optimizer": {"name": "sgd", "lr": 0.1},
                    "loss": "cross_entropy",
                    "metrics": [],
                    "epochs": epochs,
                    "seed": 0,
                    "data": {
                        "train": {
                            "name": "synthetic_classification",
                            "n": 32,
                            "dim": 6,
                            "num_classes": 4,
                            "batch_size": 8,
                        }
                    },
                    "storage_root": str(tmp_path / "storage"),
                    "project": "examples",
                    "dag_name": "resume-demo",
                },
            }
        },
    }


def test_train_resumes_after_restart(tmp_db, tmp_path):
    # first run: 1 epoch (4 steps), checkpoints, exits — the "interrupted" run
    statuses = run_dag_local(
        _dag(tmp_path, epochs=1), db_path=tmp_db, workdir=str(tmp_path)
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values())

    # second run: same storage, target 3 epochs — must restore step 4 and
    # run only epochs 1 and 2
    statuses = run_dag_local(
        _dag(tmp_path, epochs=3), db_path=tmp_db, workdir=str(tmp_path)
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values())

    store = Store(tmp_db)
    rows2 = {r["name"]: r for r in store.task_rows(2)}
    trow = rows2["train"]

    logs = " ".join(l["message"] for l in store.task_logs(trow["id"]))
    assert "resumed from checkpoint step 4" in logs

    # epoch numbering continues: only epochs 1 and 2 ran in the second task
    series = store.metric_series(trow["id"], "train/loss")
    assert [s for s, _ in series] == [1, 2]

    # final optimizer step = 3 epochs * 4 steps
    result = json.loads(trow["result"])
    assert result["final"] is not None
    from mlcomp_tpu.io.checkpoint import latest_step

    assert latest_step(result["ckpt_dir"]) == 12
    store.close()


def test_resume_disabled_restarts_from_scratch(tmp_db, tmp_path):
    run_dag_local(_dag(tmp_path, epochs=1), db_path=tmp_db, workdir=str(tmp_path))
    cfg = _dag(tmp_path, epochs=1)
    cfg["executors"]["train"]["args"]["resume"] = False
    statuses = run_dag_local(cfg, db_path=tmp_db, workdir=str(tmp_path))
    assert all(s == TaskStatus.SUCCESS for s in statuses.values())
    store = Store(tmp_db)
    rows = {r["name"]: r for r in store.task_rows(2)}
    logs = " ".join(l["message"] for l in store.task_logs(rows["train"]["id"]))
    assert "resumed" not in logs
    # fresh run logged epoch 0 again
    series = store.metric_series(rows["train"]["id"], "train/loss")
    assert [s for s, _ in series] == [0]
    store.close()


def test_independent_runs_do_not_collide_in_storage(tmp_path):
    """Two separate submissions (fresh DBs, same project/task names, same
    storage root) must not resume each other's checkpoints — the second
    run here has a different model width and would crash on restore."""
    import copy

    cfg = _dag(tmp_path, epochs=1)
    cfg = copy.deepcopy(cfg)
    del cfg["executors"]["train"]["args"]["dag_name"]  # default namespace
    statuses = run_dag_local(
        cfg, db_path=str(tmp_path / "a.sqlite"), workdir=str(tmp_path)
    )
    assert all(s.value == "success" for s in statuses.values())

    cfg2 = copy.deepcopy(cfg)
    model = cfg2["executors"]["train"]["args"]["model"]
    model["hidden"] = [h * 2 for h in model["hidden"]]
    statuses = run_dag_local(
        cfg2, db_path=str(tmp_path / "b.sqlite"), workdir=str(tmp_path)
    )
    assert all(s.value == "success" for s in statuses.values())


def test_async_writer_overlapped_saves(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlcomp_tpu.io.checkpoint import (
        AsyncCheckpointWriter,
        latest_step,
        restore_checkpoint,
    )

    tree = {"w": jnp.arange(8.0), "step": jnp.zeros(())}
    with AsyncCheckpointWriter(tmp_path / "ck", max_to_keep=2) as w:
        for step in range(5):
            w.save(jax.tree.map(lambda x: x + step, tree), step=step)
    assert latest_step(tmp_path / "ck") == 4
    restored = restore_checkpoint(tmp_path / "ck", tree)
    np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(8.0) + 4)
    # retention honors max_to_keep across async saves
    kept = sorted(
        int(p.name) for p in (tmp_path / "ck").iterdir() if p.name.isdigit()
    )
    assert len(kept) <= 2 and kept[-1] == 4


def test_eval_restore_ignores_optimizer_mismatch(tmp_path):
    """valid/infer/generate stages restore weights-only: a train task's
    adamw+grad-clip opt_state tree must not be required downstream."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlcomp_tpu.io.checkpoint import restore_eval_state, save_checkpoint
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.optim import create_optimizer
    from mlcomp_tpu.train.state import TrainState, init_model

    model = create_model({"name": "mlp", "num_classes": 4, "hidden": [8]})
    params, ms = init_model(
        model, {"x": jnp.zeros((1, 6))}, jax.random.PRNGKey(0)
    )
    train_tx = create_optimizer(
        {"name": "adamw", "lr": 1e-3, "grad_clip": 1.0}
    )
    trained = TrainState.create(model.apply, params, train_tx, ms,
                                ema_decay=0.9)
    trained = trained.replace(step=jnp.asarray(7, jnp.int32))
    save_checkpoint(tmp_path / "ck", trained, step=3)

    eval_tx = create_optimizer({"name": "sgd", "lr": 0.1})
    p2, ms2 = init_model(model, {"x": jnp.zeros((1, 6))}, jax.random.PRNGKey(1))
    fresh = TrainState.create(model.apply, p2, eval_tx, ms2)
    restored = restore_eval_state(tmp_path / "ck", fresh)
    # EMA weights become the params (trained state tracked EMA)
    for a, b in zip(
        jax.tree.leaves(restored.params), jax.tree.leaves(trained.ema_params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert int(restored.step) == 7  # internal counter, not the ckpt index
    assert restored.ema_params is None

    # non-EMA checkpoint: plain params restore through the probe fallback
    plain = TrainState.create(model.apply, params, train_tx, ms)
    plain = plain.replace(
        params=jax.tree.map(lambda p: p + 1.0, plain.params)
    )
    save_checkpoint(tmp_path / "ck2", plain, step=1)
    restored2 = restore_eval_state(tmp_path / "ck2", fresh)
    for a, b in zip(
        jax.tree.leaves(restored2.params), jax.tree.leaves(plain.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

"""Content-hash directory sync (master→worker code distribution)."""

import sys

from mlcomp_tpu.io.sync import dir_manifest, snapshot_code, sync_dirs


def _mk(root, files):
    for rel, content in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)


def test_manifest_hashes_and_excludes(tmp_path):
    _mk(
        tmp_path,
        {
            "pkg/mod.py": "x = 1",
            "pkg/__pycache__/mod.cpython-311.pyc": "junk",
            ".git/HEAD": "ref",
            "data.txt": "hello",
        },
    )
    m = dir_manifest(tmp_path)
    assert set(m) == {"pkg/mod.py", "data.txt"}
    m2 = dir_manifest(tmp_path)
    assert m == m2  # deterministic


def test_sync_copies_changes_and_deletes_stale(tmp_path):
    src, dst = tmp_path / "src", tmp_path / "dst"
    _mk(src, {"a.py": "1", "sub/b.py": "2"})
    copied, removed = sync_dirs(src, dst)
    assert copied == ["a.py", "sub/b.py"] and removed == []
    assert (dst / "sub/b.py").read_text() == "2"

    # no-op second pass
    assert sync_dirs(src, dst) == ([], [])

    # change one, delete one, add one
    _mk(src, {"a.py": "1-changed", "c.py": "3"})
    (src / "sub/b.py").unlink()
    copied, removed = sync_dirs(src, dst)
    assert copied == ["a.py", "c.py"] and removed == ["sub/b.py"]
    assert not (dst / "sub").exists()  # empty dirs pruned


def test_snapshot_code_roundtrip(tmp_path):
    proj = tmp_path / "proj"
    _mk(proj, {"exec.py": "print('hi')"})
    snap = snapshot_code(proj, tmp_path / "storage", "myproj")
    assert snap.endswith("code/myproj")
    m = dir_manifest(snap)
    assert set(m) == {"exec.py"}


def test_worker_sync_makes_code_importable(tmp_db, tmp_path):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.scheduler.worker import Worker

    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="a", executor="noop"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]

    src = tmp_path / "snap"
    _mk(src, {"user_mod_sync_test.py": "MAGIC = 41"})
    workdir = tmp_path / "work"
    workdir.mkdir()
    w = Worker(store, name="w0", workdir=str(workdir), load_jax_executors=False)
    dest = str(workdir / "code")
    try:
        w._sync_code({"code_src": str(src)}, tid)
        assert (workdir / "code/user_mod_sync_test.py").exists()
        assert dest in sys.path
        import user_mod_sync_test

        assert user_mod_sync_test.MAGIC == 41
        logs = " ".join(l["message"] for l in store.task_logs(tid))
        assert "code sync: 1 copied" in logs
    finally:
        sys.path.remove(dest)
        sys.modules.pop("user_mod_sync_test", None)
        store.close()


def test_dag_with_code_dir_runs_user_executor(tmp_db, tmp_path):
    """End-to-end: info.code_dir ships a user-defined executor to workers."""
    import sys

    from mlcomp_tpu.dag.schema import TaskStatus
    from mlcomp_tpu.scheduler.local import run_dag_local

    proj = tmp_path / "proj"
    _mk(
        proj,
        {
            "my_executors.py": (
                "from mlcomp_tpu.executors.base import Executor\n"
                "class Hello(Executor):\n"
                "    name = 'hello_from_user_code'\n"
                "    def work(self, ctx):\n"
                "        ctx.log('user code ran')\n"
                "        return {'answer': 42}\n"
            )
        },
    )
    cfg = {
        "info": {
            "name": "usercode",
            "project": "p",
            "code_dir": str(proj),
            "code_import": "my_executors",
            "storage_root": str(tmp_path / "storage"),
        },
        "executors": {"hello": {"type": "hello_from_user_code"}},
    }
    workdir = tmp_path / "work"
    workdir.mkdir()
    dest = str(workdir / "code")
    try:
        statuses = run_dag_local(cfg, db_path=tmp_db, workdir=str(workdir))
        assert statuses == {"hello": TaskStatus.SUCCESS}
        import json as _json

        from mlcomp_tpu.db.store import Store

        store = Store(tmp_db)
        row = store.task_rows(1)[0]
        assert _json.loads(row["result"]) == {"answer": 42}
        logs = " ".join(l["message"] for l in store.task_logs(row["id"]))
        assert "user code ran" in logs
        store.close()
    finally:
        if dest in sys.path:
            sys.path.remove(dest)
        sys.modules.pop("my_executors", None)


def test_sync_missing_src_raises_not_wipes(tmp_path):
    import pytest

    dst = tmp_path / "dst"
    _mk(dst, {"warm.py": "x"})
    with pytest.raises(FileNotFoundError):
        sync_dirs(tmp_path / "nope", dst)
    assert (dst / "warm.py").exists()  # warm copy preserved


def test_bad_code_import_fails_task_not_worker(tmp_db, tmp_path):
    """Setup errors (typo'd code_import) fail the task; the worker survives."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.scheduler.supervisor import Supervisor
    from mlcomp_tpu.scheduler.worker import Worker

    proj = tmp_path / "proj"
    _mk(proj, {"ok.py": "pass"})
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(
            name="d",
            project="p",
            tasks=(
                TaskSpec(
                    name="a",
                    executor="noop",
                    args={"code_src": str(proj), "code_import": ["no_such_module"]},
                ),
            ),
        )
    )
    sup = Supervisor(store)
    sup.tick()
    w = Worker(store, name="w0", workdir=str(tmp_path / "wk"), load_jax_executors=False)
    assert w.run_once() is True  # ran (and failed) the task; did not raise
    sup.tick()
    assert store.task_statuses(dag_id)["a"] == TaskStatus.FAILED
    row = store.task_rows(dag_id)[0]
    assert "no_such_module" in row["error"]
    store.close()

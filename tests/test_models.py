"""Shape/grad/finiteness tests across the model zoo (tiny configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model


def _init_and_forward(model, x, train=False):
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if train and "batch_stats" in variables:
        out, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
    else:
        out = model.apply(variables, x, train=train)
    return variables, out


def test_resnet50_shapes_and_finite():
    m = create_model({"name": "resnet50", "num_classes": 10, "width": 16, "dtype": "float32"})
    x = jnp.ones((2, 64, 64, 3))
    variables, out = _init_and_forward(m, x)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
    assert "batch_stats" in variables  # BN statistics tracked
    assert np.all(np.isfinite(np.asarray(out)))


def test_resnet_train_mode_updates_stats():
    m = create_model({"name": "resnet18", "num_classes": 4, "width": 8, "dtype": "float32"})
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    _, updated = m.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(variables["batch_stats"])[0]
    after = jax.tree.leaves(updated["batch_stats"])[0]
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_unet_shapes():
    m = create_model(
        {"name": "unet", "num_classes": 5, "features": [8, 16, 32], "dtype": "float32"}
    )
    x = jnp.ones((2, 64, 64, 3))
    _, out = _init_and_forward(m, x)
    assert out.shape == (2, 64, 64, 5)
    assert out.dtype == jnp.float32


def test_bert_classifier_and_mlm():
    cfg = dict(vocab_size=100, hidden=32, layers=2, heads=2, mlp_dim=64, max_len=16, dtype="float32")
    x = jnp.asarray(np.random.RandomState(0).randint(1, 100, (2, 16)))
    m = create_model({"name": "bert", "num_classes": 3, **cfg})
    _, out = _init_and_forward(m, x)
    assert out.shape == (2, 3)
    mlm = create_model({"name": "bert", "num_classes": None, **cfg})
    _, out2 = _init_and_forward(mlm, x)
    assert out2.shape == (2, 16, 100)


def test_bert_padding_mask_blocks_pad_influence():
    """Changing the NUMBER of trailing pad (id 0) slots vs real-token slots
    must change output, while the masked pads themselves must not leak into
    the CLS representation: compare same real prefix with different garbage
    beyond an attention-masked region by toggling a real token instead."""
    cfg = dict(vocab_size=50, hidden=16, layers=1, heads=2, mlp_dim=32, max_len=8, dtype="float32")
    m = create_model({"name": "bert", "num_classes": 2, **cfg})
    rs = np.random.RandomState(0)
    real = rs.randint(1, 50, (1, 4))
    a = np.concatenate([real, np.zeros((1, 4), int)], axis=1)  # 4 real + 4 pad
    variables = m.init(jax.random.PRNGKey(0), jnp.asarray(a), train=False)
    out_a = np.asarray(m.apply(variables, jnp.asarray(a), train=False))
    # pads are masked: CLS output must not depend on how many pads follow
    a_short = np.concatenate([real, np.zeros((1, 2), int)], axis=1)
    out_short = np.asarray(m.apply(variables, jnp.asarray(a_short), train=False))
    assert np.allclose(out_a, out_short, atol=1e-5)
    # real tokens are NOT masked: changing one must change the output
    b = a.copy()
    b[0, 2] = (b[0, 2] % 49) + 1
    out_b = np.asarray(m.apply(variables, jnp.asarray(b), train=False))
    assert not np.allclose(out_a, out_b, atol=1e-5)


def test_transformer_lm_causality():
    cfg = {"name": "transformer_lm", "vocab_size": 64, "hidden": 32, "layers": 2,
           "heads": 4, "dtype": "float32"}
    m = create_model(cfg)
    rs = np.random.RandomState(0)
    x1 = rs.randint(0, 64, (1, 12))
    x2 = x1.copy()
    x2[0, -1] = (x2[0, -1] + 1) % 64  # change ONLY the last token
    variables = m.init(jax.random.PRNGKey(0), jnp.asarray(x1), train=False)
    o1 = np.asarray(m.apply(variables, jnp.asarray(x1), train=False))
    o2 = np.asarray(m.apply(variables, jnp.asarray(x2), train=False))
    # causal: logits at positions < last must be unchanged
    assert np.allclose(o1[0, :-1], o2[0, :-1], atol=1e-5)
    assert not np.allclose(o1[0, -1], o2[0, -1])


def test_transformer_gqa():
    cfg = {"name": "transformer_lm", "vocab_size": 64, "hidden": 32, "layers": 1,
           "heads": 4, "kv_heads": 2, "dtype": "float32"}
    m = create_model(cfg)
    x = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
    _, out = _init_and_forward(m, x)
    assert out.shape == (2, 8, 64)


def test_models_have_gradients():
    m = create_model({"name": "resnet50", "num_classes": 4, "width": 8, "dtype": "float32"})
    # random input: constant input would be zeroed by train-mode BN
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    variables = dict(m.init(jax.random.PRNGKey(0), x, train=False))
    params = variables.pop("params")

    def loss(p):
        out, _ = m.apply(
            {"params": p, **variables}, x, train=True, mutable=["batch_stats"]
        )
        return jnp.mean(out**2)

    grads = jax.grad(loss)(params)
    norms = [float(jnp.abs(g).max()) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert any(n > 0 for n in norms)


def test_vit_forward_and_trains():
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "vit_tiny", "num_classes": 4, "patch": 8,
                  "dtype": "float32"},
        "optimizer": {"name": "lars", "lr": 0.1},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "epochs": 1,
        "data": {
            "train": {"name": "synthetic_images", "n": 16, "image": 32,
                      "num_classes": 4, "batch_size": 8}
        },
    }
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_vit_cls_pooling():
    import jax
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.state import init_model

    m = create_model({"name": "vit_tiny", "num_classes": 3, "patch": 8,
                      "pool": "cls", "dtype": "float32"})
    x = jnp.zeros((2, 32, 32, 3))
    params, state = init_model(m, {"x": x}, jax.random.PRNGKey(0))
    out = m.apply({"params": params, **state}, x)
    assert out.shape == (2, 3)


def test_lars_optimizer_builds():
    from mlcomp_tpu.train.optim import create_optimizer

    tx = create_optimizer({"name": "lars", "lr": 0.5, "weight_decay": 1e-4})
    assert tx is not None


def test_transformer_remat_matches_plain():
    """remat=True changes memory, not math: forward and gradients match."""
    import jax
    import numpy as np
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.state import init_model

    cfg = {"name": "transformer_lm", "vocab_size": 32, "hidden": 16,
           "layers": 2, "heads": 2, "dtype": "float32"}
    x = jnp.asarray(np.random.RandomState(0).randint(1, 32, (2, 8)))
    plain = create_model(cfg)
    remat = create_model({**cfg, "remat": True})
    params, _ = init_model(plain, {"x": x}, jax.random.PRNGKey(0))

    def loss(m, p):
        return jnp.sum(m.apply({"params": p}, x) ** 2)

    np.testing.assert_allclose(
        float(loss(plain, params)), float(loss(remat, params)), rtol=1e-6
    )
    gp = jax.grad(lambda p: loss(plain, p))(params)
    gr = jax.grad(lambda p: loss(remat, p))(params)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

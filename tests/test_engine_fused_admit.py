"""Fused prefill+decode dispatch (engine ``fused_admission``, default
on): an admission's chunks ride the decode dispatches instead of
running as lone dispatches at drained boundaries.  The acceptance
contract: decode rows AND the admitted request's tokens are
bit-identical between the fused and staged paths — on both cache
layouts, across pipeline depths, through a prefix-cache hit landing
mid-admission, and with EOS retiring a neighbour mid-prefill — and a
fault inside the fused prep fails ONLY the admitting request."""

import functools
import queue

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import DecodeEngine
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import GenerationService
from mlcomp_tpu.train.state import init_model
from mlcomp_tpu.utils import faults


@functools.lru_cache(maxsize=None)
def _model_and_params(kv_quant=False, seed=0):
    # cached across tests: init is deterministic per (kv_quant, seed)
    # and nothing mutates the returned pytree
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


def _reference(model, params, ids, n_new, bucket=16, **kw):
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask), **kw,
    )
    return np.asarray(out)[0, bucket:].tolist()


IDS_A = [3, 14, 15, 9, 2]
IDS_B = [7, 3, 44, 5, 6]

# compiled-program cache across same-config engines (the bench.py
# sharing idiom): fused/staged/pipeline-depth are host-side knobs, so
# every engine a workload key builds runs the identical program set —
# compile once per key instead of once per engine
_FNS: dict = {}


def _share_fns(eng, key):
    eng._fns.update(_FNS.setdefault(key, {}))
    return eng


def _overlapped_workload(model, params, fused, depth=2, prefill_chunk=4,
                         fns_key=None):
    """A decodes while B's multi-chunk admission runs — with
    prefill_chunk=4 in the 16 bucket, B (5 real tokens, start pad 11)
    runs chunks 2 and 3, both overlapped with A's decode.  Returns the
    comparable outputs plus the engine stats."""
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=12,
                       steps_per_dispatch=2, pipeline_depth=depth,
                       prefill_chunk=prefill_chunk,
                       fused_admission=fused)
    if fns_key is not None:
        _share_fns(eng, fns_key)
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit(IDS_A, 10, logprobs=True, stream=qa)
        qa.get(timeout=300)                    # A is decoding
        fb = eng.submit(IDS_B, 6, logprobs=True)
        ra = fa.result(timeout=300)
        rb = fb.result(timeout=300)
        st = eng.stats()
    finally:
        if fns_key is not None:
            _FNS[fns_key].update(eng._fns)
        eng.close()
    return {"a": (ra["ids"], ra["logprobs"]),
            "b": (rb["ids"], rb["logprobs"])}, st


@pytest.mark.parametrize("kv_quant", [False, True])
def test_fused_bit_identical_to_staged(kv_quant):
    """The acceptance equality: with B's admission overlapping A's
    decode, fused and staged engines emit bit-identical tokens AND
    logprobs for both the decode rows and the admitted request (its
    first token comes from the fused program's chunk half), on both
    cache layouts — and both match bare generate."""
    model, params = _model_and_params(kv_quant)
    key = ("workload", kv_quant)
    fused, st_f = _overlapped_workload(model, params, True, fns_key=key)
    staged, st_s = _overlapped_workload(model, params, False, fns_key=key)
    assert fused == staged
    assert fused["a"][0] == _reference(model, params, IDS_A, 10)
    assert fused["b"][0] == _reference(model, params, IDS_B, 6)
    # counter contract: a fused chunk counts exactly like a staged one
    # (no double count), and the overlapped admission is recorded
    assert st_f["prefill_chunks"] == st_s["prefill_chunks"]
    assert st_f["prefills"] == st_s["prefills"] == 2
    assert st_f["fused_chunks"] == 2        # B's two run chunks
    assert st_f["admissions_overlapped"] == 1
    assert st_s["fused_chunks"] == 0
    assert st_s["admissions_overlapped"] == 0
    assert st_f["fused_admission"] is True
    assert st_s["fused_admission"] is False


def test_fused_depth1_vs_depth2():
    """The fused path composes with the dispatch pipeline: depth 1 and
    depth 2 emit identical outputs with an admission in flight."""
    model, params = _model_and_params()
    key = ("workload", False)
    d1, _ = _overlapped_workload(model, params, True, depth=1, fns_key=key)
    d2, _ = _overlapped_workload(model, params, True, depth=2, fns_key=key)
    assert d1 == d2


def test_prefix_cache_hit_mid_admission_fused():
    """A prefix-cache hit landing mid-admission keeps its
    chunk-skipping semantics on the fused path: the suffix chunk rides
    a decode dispatch, tokens stay exact vs the cold run and vs the
    staged engine, and hit accounting is identical."""
    from mlcomp_tpu.cache import PrefixKVCache

    model, params = _model_and_params()
    shared = [9, 10, 11, 12, 13, 14, 15, 16, 17]   # 9 real tokens
    results = {}
    for fused in (True, False):
        cache = PrefixKVCache(max_bytes=1 << 22)
        eng = _share_fns(
            DecodeEngine(model, {"params": params}, slots=2,
                         prompt_buckets=(16,), max_new_cap=12,
                         steps_per_dispatch=2, prefill_chunk=4,
                         prefix_cache=cache, fused_admission=fused),
            ("workload", False),   # same program set as the workload
        )
        try:
            cold = eng.submit(shared, 6).result(timeout=300)
            cache.flush()                 # capture lands in the trie
            qa: "queue.Queue" = queue.Queue()
            fa = eng.submit(IDS_A, 10, stream=qa)
            qa.get(timeout=300)           # A is decoding
            hit = eng.submit(shared, 6).result(timeout=300)
            ra = fa.result(timeout=300)
            st = eng.stats()
        finally:
            _FNS[("workload", False)].update(eng._fns)
            eng.close()
        assert cold["cache_hit_tokens"] == 0
        # 9 tokens, start pad 7, chunk 4: hit covers through chunk 2's
        # boundary (12 slots) -> 5 prompt tokens skip their prefill
        assert hit["cache_hit_tokens"] == 5, hit
        assert hit["ids"] == cold["ids"]
        results[fused] = (cold["ids"], hit["ids"], ra["ids"], st["prefills"])
    assert results[True] == results[False]
    assert results[True][0] == _reference(model, params, shared, 6)


def test_eos_during_overlapped_admission():
    """A hits EOS while B's fused admission is mid-flight: A's slot
    frees and its stream terminates correctly, B's insert still lands,
    and everything matches the staged path."""
    model, params = _model_and_params()
    # A stops at its second greedy token (deterministic reference)
    eos_a = _reference(model, params, IDS_A, 2)[1]
    results = {}
    for fused in (True, False):
        eng = _share_fns(
            DecodeEngine(model, {"params": params}, slots=2,
                         prompt_buckets=(16,), max_new_cap=12,
                         steps_per_dispatch=1, prefill_chunk=2,
                         fused_admission=fused),
            ("eos", 1, 2),
        )
        try:
            qa: "queue.Queue" = queue.Queue()
            fa = eng.submit(IDS_A, 12, eos_id=eos_a, stream=qa)
            qa.get(timeout=300)           # A is decoding
            fb = eng.submit(IDS_B, 6)     # 6+ chunks of 2: a long prefill
            ra = fa.result(timeout=300)
            rb = fb.result(timeout=300)
        finally:
            _FNS[("eos", 1, 2)].update(eng._fns)
            eng.close()
        assert ra["ids"][-1] == eos_a and len(ra["ids"]) == 2, ra
        results[fused] = (ra["ids"], rb["ids"])
    assert results[True] == results[False]
    assert results[True][1] == _reference(model, params, IDS_B, 6)


def test_fused_prefill_fault_fails_only_the_admission():
    """The engine.fused_prefill chaos point (host-side prep, before the
    combined device call): the admitting request fails with the fault,
    the decode fleet's tokens stay bit-identical to a fault-free run,
    the engine stays healthy, and the next admission succeeds."""
    model, params = _model_and_params()
    ref_a = _reference(model, params, IDS_A, 10)
    ref_b = _reference(model, params, IDS_B, 6)
    eng = _share_fns(
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=12,
                     steps_per_dispatch=2, prefill_chunk=4),
        ("workload", False),
    )
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit(IDS_A, 10, stream=qa)
        qa.get(timeout=300)               # A is decoding
        faults.arm("engine.fused_prefill", flavor="raise", times=1)
        fb = eng.submit(IDS_B, 6)
        with pytest.raises(faults.FaultInjected):
            fb.result(timeout=300)
        # survivor exact, engine alive, no admission state leaked
        assert fa.result(timeout=300)["ids"] == ref_a
        assert eng.healthy
        assert eng._adm is None
        # the slot the failed admission never took is still usable
        rb = eng.submit(IDS_B, 6).result(timeout=300)
        assert rb["ids"] == ref_b
        st = eng.stats()
        assert st["prefills"] == 2        # A + the retry, not the fault
        assert st["active_slots"] == 0 or st["active_slots"] == 1
    finally:
        faults.disarm_all()
        eng.close()


def test_staged_flag_plumbing_and_metrics():
    """--engine-staged-admission plumbing: the service forwards
    engine_fused_admission (rejected off the continuous batcher), the
    engine reports the mode in stats(), and the new admission metrics
    (fused chunk / overlap counters + the stall histogram) are in the
    exposition."""
    model, params = _model_and_params()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
        engine_fused_admission=False,
    )
    try:
        assert svc.engine.fused_admission is False
        svc.generate([5, 6, 7], 4)
        assert svc.stats()["engine"]["fused_admission"] is False
        text = svc.metrics.render()
        for name in ("mlcomp_engine_fused_prefill_chunks_total",
                     "mlcomp_engine_admissions_overlapped_total",
                     "mlcomp_engine_admission_stall_ms_bucket"):
            assert name in text, name
    finally:
        svc.close()
    with pytest.raises(ValueError, match="continuous"):
        GenerationService(
            model, {"params": params}, batcher="window", batch_sizes=(1,),
            prompt_buckets=(16,), max_new_buckets=(8,),
            engine_fused_admission=False,
        )
    # default is fused; warmup precompiles the fused program family
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
    )
    try:
        assert svc.engine.fused_admission is True
        # one chunk width x every ladder rung (the serve default is
        # adaptive K, so the fused family precompiles per rung)
        ladder = svc.engine.k_ladder
        assert svc.engine.warm_fused_fns() == len(ladder)
        for k in ladder:
            assert ("fused_dispatch", 16, k) in svc.engine._fns
    finally:
        svc.close()

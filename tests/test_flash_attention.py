"""Flash attention kernel vs the XLA reference path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.ops.attention import reference_attention
from mlcomp_tpu.ops.pallas.flash_attention import flash_attention


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).normal(size=shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q = _rand((2, 256, 2, 64), 0)
    k = _rand((2, 256, 2, 64), 1)
    v = _rand((2, 256, 2, 64), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_kv=128)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_gqa_and_cross_lengths():
    # 4 query heads sharing 2 kv heads; Sq != Sk
    q = _rand((1, 256, 4, 64), 0)
    k = _rand((1, 384, 2, 64), 1)
    v = _rand((1, 384, 2, 64), 2)
    out = flash_attention(q, k, v, block_q=128, block_kv=128)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q = _rand((1, 128, 2, 64), 3)
    k = _rand((1, 128, 2, 64), 4)
    v = _rand((1, 128, 2, 64), 5)
    w = _rand((1, 128, 2, 64), 6)  # fixed cotangent-shaping weights

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_kv=128) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_grads_gqa():
    q = _rand((1, 128, 4, 64), 7)
    k = _rand((1, 128, 2, 64), 8)
    v = _rand((1, 128, 2, 64), 9)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=128, block_kv=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_small_sequences_fall_back():
    q = _rand((1, 64, 2, 64), 0)
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)


def test_dispatch_env_off(monkeypatch):
    from mlcomp_tpu.ops.attention import dot_product_attention

    monkeypatch.setenv("MLCOMP_TPU_FLASH", "off")
    q = _rand((1, 128, 2, 64), 0)
    out = dot_product_attention(q, q, q, causal=True)
    ref = reference_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

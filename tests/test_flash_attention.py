"""Flash attention kernel vs the XLA reference path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.ops.attention import reference_attention
from mlcomp_tpu.ops.pallas.flash_attention import flash_attention


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).normal(size=shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q = _rand((2, 256, 2, 64), 0)
    k = _rand((2, 256, 2, 64), 1)
    v = _rand((2, 256, 2, 64), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_kv=128)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_gqa_and_cross_lengths():
    # 4 query heads sharing 2 kv heads; Sq != Sk
    q = _rand((1, 256, 4, 64), 0)
    k = _rand((1, 384, 2, 64), 1)
    v = _rand((1, 384, 2, 64), 2)
    out = flash_attention(q, k, v, block_q=128, block_kv=128)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q = _rand((1, 128, 2, 64), 3)
    k = _rand((1, 128, 2, 64), 4)
    v = _rand((1, 128, 2, 64), 5)
    w = _rand((1, 128, 2, 64), 6)  # fixed cotangent-shaping weights

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_kv=128) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_grads_gqa():
    q = _rand((1, 128, 4, 64), 7)
    k = _rand((1, 128, 2, 64), 8)
    v = _rand((1, 128, 2, 64), 9)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=128, block_kv=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_small_sequences_fall_back():
    q = _rand((1, 64, 2, 64), 0)
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_length_stays_on_kernel(causal):
    """S % 128 != 0 pads to a block multiple instead of falling back."""
    s = 777
    q = _rand((1, s, 2, 64), 20)
    k = _rand((1, s, 2, 64), 21)
    v = _rand((1, s, 2, 64), 22)
    out = flash_attention(q, k, v, causal=causal)
    assert out.shape == q.shape
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_length_grads(causal):
    s = 333
    q = _rand((1, s, 2, 64), 23)
    k = _rand((1, s, 2, 64), 24)
    v = _rand((1, s, 2, 64), 25)
    w = _rand((1, s, 2, 64), 26)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_ragged_length_with_kv_stop():
    """Ragged S composes with caller-provided key windows."""
    b, s_q, s_k = 2, 200, 300
    q = _rand((b, s_q, 2, 64), 27)
    k = _rand((b, s_k, 2, 64), 28)
    v = _rand((b, s_k, 2, 64), 29)
    stop = jnp.asarray([300, 170], jnp.int32)
    out = flash_attention(q, k, v, kv_stop=stop)
    ref = reference_attention(
        q, k, v, mask=_window_mask(b, s_k, np.zeros(b, np.int64), np.asarray(stop))
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_causal_block_skip_numerics():
    """Multi-block causal (exercises the dead-block index clamping in all
    three kernels) still matches the reference bit-for-bit-ish."""
    s = 384
    q = _rand((1, s, 2, 64), 30)
    k = _rand((1, s, 2, 64), 31)
    v = _rand((1, s, 2, 64), 32)
    w = _rand((1, s, 2, 64), 33)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=128, block_kv=128) * w
        )

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * w)

    np.testing.assert_allclose(
        float(loss_flash(q, k, v)), float(loss_ref(q, k, v)), rtol=1e-5
    )
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_dispatch_env_off(monkeypatch):
    from mlcomp_tpu.ops.attention import dot_product_attention

    monkeypatch.setenv("MLCOMP_TPU_FLASH", "off")
    q = _rand((1, 128, 2, 64), 0)
    out = dot_product_attention(q, q, q, causal=True)
    ref = reference_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def _window_mask(b, s_k, lo, hi):
    cols = np.arange(s_k)[None]
    return jnp.asarray(
        ((cols >= np.asarray(lo)[:, None]) & (cols < np.asarray(hi)[:, None]))
    )[:, None, None, :]


@pytest.mark.parametrize("causal", [False, True])
def test_kv_bounds_match_masked_reference(causal):
    """Per-row [start, stop) key windows == the equivalent dense mask."""
    b, s = 3, 256
    q = _rand((b, s, 4, 64), 10)
    k = _rand((b, s, 2, 64), 11)
    v = _rand((b, s, 2, 64), 12)
    lo = np.asarray([0, 17, 128])
    hi = np.asarray([256, 256, 200])
    out = flash_attention(
        q, k, v, causal=causal,
        kv_start=jnp.asarray(lo), kv_stop=jnp.asarray(hi),
        block_q=128, block_kv=128,
    )
    ref = reference_attention(
        q, k, v, causal=causal, mask=_window_mask(b, s, lo, hi)
    )
    out_np, ref_np = np.asarray(out), np.asarray(ref)
    if causal:
        # rows whose causal∩window key set is empty: kernel outputs 0 by
        # contract, the XLA path degrades to a uniform average — compare
        # only rows with at least one valid key
        rows = np.arange(s)[None] >= lo[:, None]          # (B, S)
        np.testing.assert_allclose(
            out_np[rows], ref_np[rows], atol=2e-5
        )
        np.testing.assert_allclose(
            out_np[~rows], np.zeros_like(out_np[~rows]), atol=1e-6
        )
    else:
        np.testing.assert_allclose(out_np, ref_np, atol=2e-5)


def test_kv_bounds_grads_match_masked_reference():
    b, s = 2, 128
    q = _rand((b, s, 2, 64), 13)
    k = _rand((b, s, 2, 64), 14)
    v = _rand((b, s, 2, 64), 15)
    w = _rand((b, s, 2, 64), 16)
    lo = jnp.asarray([5, 0], jnp.int32)
    hi = jnp.asarray([128, 100], jnp.int32)
    mask = _window_mask(b, s, np.asarray(lo), np.asarray(hi))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, kv_start=lo, kv_stop=hi,
                            block_q=128, block_kv=128) * w
        )

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, mask=mask) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5)


def test_bounded_scheduled_matches_rectangular(monkeypatch):
    """r3: the compressed dynamic-grid bounded path (default) must equal
    the rectangular pl.when path bit-for-bit on CPU (same block compute,
    different iteration) — fwd and grads, GQA, multi-block windows,
    including an empty-window row."""
    from mlcomp_tpu.ops.pallas import flash_attention as fa

    b, s = 4, 512
    q = _rand((b, s, 4, 64), 30)
    k = _rand((b, s, 2, 64), 31)
    v = _rand((b, s, 2, 64), 32)
    w = _rand((b, s, 4, 64), 33)
    lo = jnp.asarray([0, 64, 200, 70], jnp.int32)
    hi = jnp.asarray([512, 384, 200, 71], jnp.int32)  # row 2: EMPTY window

    def loss(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, kv_start=lo, kv_stop=hi,
                               block_q=128, block_kv=128) * w
        )

    def run():
        out = fa.flash_attention(q, k, v, kv_start=lo, kv_stop=hi,
                                 block_q=128, block_kv=128)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return out, g

    monkeypatch.setenv("MLCOMP_FLASH_BOUNDED_SCHED", "0")
    out_rect, g_rect = run()
    monkeypatch.setenv("MLCOMP_FLASH_BOUNDED_SCHED", "1")
    out_sched, g_sched = run()
    np.testing.assert_array_equal(np.asarray(out_rect), np.asarray(out_sched))
    for a, b_ in zip(g_rect, g_sched):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    # the empty-window row outputs exact zeros on both paths
    np.testing.assert_array_equal(
        np.asarray(out_sched[2]), np.zeros_like(np.asarray(out_sched[2]))
    )


def test_ragged_causal_scheduled_matches_rectangular(monkeypatch):
    """r3 late: causal + per-row windows (left-padded decode prefill) on
    the compressed dynamic grid must equal the rectangular causal path
    bit-for-bit — fwd and all three grads, GQA, including a row whose
    window∩causal intersection is empty for early q blocks."""
    from mlcomp_tpu.ops.pallas import flash_attention as fa

    b, s = 4, 512
    q = _rand((b, s, 4, 64), 40)
    k = _rand((b, s, 2, 64), 41)
    v = _rand((b, s, 2, 64), 42)
    w = _rand((b, s, 4, 64), 43)
    # lo = left-pad prefix; row 3's window starts past the first THREE
    # q blocks' causal reach (rows < 384 see no valid key at all)
    lo = jnp.asarray([0, 64, 200, 384], jnp.int32)
    hi = jnp.full((b,), s, jnp.int32)

    def loss(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, causal=True, kv_start=lo,
                               kv_stop=hi, block_q=128, block_kv=128) * w
        )

    def run():
        out = fa.flash_attention(q, k, v, causal=True, kv_start=lo,
                                 kv_stop=hi, block_q=128, block_kv=128)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return out, g

    monkeypatch.setenv("MLCOMP_FLASH_BOUNDED_SCHED_CAUSAL", "0")
    out_rect, g_rect = run()
    monkeypatch.setenv("MLCOMP_FLASH_BOUNDED_SCHED_CAUSAL", "1")
    out_sched, g_sched = run()
    np.testing.assert_array_equal(np.asarray(out_rect), np.asarray(out_sched))
    for a, b_ in zip(g_rect, g_sched):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    # rows before their window start see no keys: exact zeros
    np.testing.assert_array_equal(
        np.asarray(out_sched[3, :384]),
        np.zeros_like(np.asarray(out_sched[3, :384])),
    )


def test_kv_stop_only_right_padding():
    """kv_stop alone (BERT-style right padding) via the dispatch layer."""
    from mlcomp_tpu.ops.attention import dot_product_attention

    b, s = 2, 128
    q = _rand((b, s, 2, 64), 17)
    k = _rand((b, s, 2, 64), 18)
    v = _rand((b, s, 2, 64), 19)
    stop = jnp.asarray([128, 64], jnp.int32)
    out = dot_product_attention(q, k, v, kv_stop=stop)
    ref = reference_attention(
        q, k, v, mask=_window_mask(b, s, np.zeros(b, np.int64), np.asarray(stop))
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

"""Paged device KV (engine ``kv_layout="paged"``, mlcomp_tpu/kvpool).

The acceptance contract: paged outputs are BIT-IDENTICAL to the dense
layout — across cache families (f32 + kv8), pipeline depths, the
speculative dispatch, mid-stream admissions, and the device
prefix-registry COW path — while admission is gated by free pages,
the slot count scales elastically, and nothing leaks a page."""

import functools
import os
import queue
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import DecodeEngine
from mlcomp_tpu.kvpool import NoFreePages, RESERVED_PAGES
from mlcomp_tpu.models import create_model
from mlcomp_tpu.serve import BackpressureError, GenerationService
from mlcomp_tpu.train.state import init_model


@functools.lru_cache(maxsize=None)
def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


IDS_A = [3, 14, 15, 9, 2, 6, 53, 58, 9, 7]
IDS_B = [7, 3, 44, 5, 6]

# share the LAYOUT-INDEPENDENT compiled programs across engines: the
# prefill chunk/init/capture programs run on the dense (1, l_buf)
# admission cache whatever the carry layout; the dispatch/insert/fused
# families close over the layout and must NOT cross it
_SHARED_KEYS = ("prefill_init",)
_FNS: dict = {}


def _engine(layout, kv_quant=False, fns_key=None, **kw):
    model, params = _model_and_params(kv_quant)
    kw.setdefault("slots", 2)
    kw.setdefault("prompt_buckets", (16,))
    kw.setdefault("max_new_cap", 12)
    if kw.get("spec_k") is None:
        kw.setdefault("steps_per_dispatch", 2)
    kw.setdefault("prefill_chunk", 4)
    if layout == "paged":
        kw["kv_layout"] = "paged"
    eng = DecodeEngine(model, {"params": params}, **kw)
    if fns_key is not None:
        pool = _FNS.setdefault((fns_key, layout, kv_quant), {})
        eng._fns.update(pool)
        eng._fns_pool = pool
    return eng


def _close(eng):
    if hasattr(eng, "_fns_pool"):
        eng._fns_pool.update(eng._fns)
    eng.close()


def _overlapped(layout, kv_quant=False, depth=2, spec_k=None):
    """A decodes while B's multi-chunk admission lands mid-stream —
    the same workload shape the fused-admission matrix certifies."""
    model, params = _model_and_params(kv_quant)
    kw = {}
    if spec_k is not None:
        kw = {"spec_k": spec_k, "steps_per_dispatch": 1}
    # the dispatch family closes over spec_k AND the paged data path
    # (fused vs lax sandwich) — keep each in its own compiled pool
    attn = os.environ.get("MLCOMP_TPU_PAGED_ATTN", "auto")
    eng = _engine(layout, kv_quant, fns_key=("mtx", spec_k, attn),
                  pipeline_depth=depth, **kw)
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit(IDS_A, 10, logprobs=spec_k is None, stream=qa)
        qa.get(timeout=300)                   # A is decoding
        fb = eng.submit(IDS_B, 6, logprobs=spec_k is None)
        ra, rb = fa.result(timeout=300), fb.result(timeout=300)
        st = eng.stats()
    finally:
        _close(eng)
    key = lambda r: (r["ids"], r.get("logprobs"))  # noqa: E731
    return {"a": key(ra), "b": key(rb)}, st


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("depth", [1, 2])
def test_paged_bit_identical_to_dense(kv_quant, depth):
    """The default paged data path is FUSED (MLCOMP_TPU_PAGED_ATTN
    auto): attention reads K/V through the page table (paged Pallas
    kernels on the kv8 family, per-layer gathers on f32) and the
    per-token append writes pages in place — no dense view, and still
    bit-identical to the dense engine.  The 10-token decode budget
    also crosses the insert's one-dispatch lookahead, so decode pages
    allocate LAZILY mid-stream (counted, never starved here)."""
    dense, _ = _overlapped("dense", kv_quant, depth=depth)
    paged, st = _overlapped("paged", kv_quant, depth=depth)
    assert paged == dense
    assert st["kv_layout"] == "paged"
    assert st["kv_pool"]["pages_total"] > 0
    assert st["kv_pages_lazy_allocated"] > 0
    assert st["kv_decode_page_failures"] == 0


def test_paged_bit_identical_spec_dispatch():
    """The speculative verify (draft + K+1-wide forward) runs fused
    too: the multi-query PAGED kernel sweeps the table-mapped pages
    once for all K+1 positions."""
    dense, _ = _overlapped("dense", spec_k=3)
    paged, _ = _overlapped("paged", spec_k=3)
    assert paged == dense


def test_fused_matches_lax_reference(monkeypatch):
    """MLCOMP_TPU_PAGED_ATTN=lax keeps the PR-7 gather/scatter
    sandwich as the everywhere-reference; the fused default must emit
    the same tokens AND logprobs on the kv8 family (the matrix above
    already pins fused == dense; this pins the reference path too, so
    a bisect between the two envs always means something)."""
    fused, _ = _overlapped("paged", True, depth=2)
    monkeypatch.setenv("MLCOMP_TPU_PAGED_ATTN", "lax")
    # _overlapped keys the shared compiled-program pool on the env, so
    # the reference engine compiles its own sandwich family instead of
    # silently reusing the fused programs
    ref, st = _overlapped("paged", True, depth=2)
    assert ref == fused
    assert st["kv_pages_lazy_allocated"] > 0  # lazy growth is
    # data-path-independent: the sandwich scatters through the same
    # lazily-extended tables


def test_registry_cow_hit_bit_identical():
    """Same-placement shared prefixes: the second request maps the
    first's prompt-prefix pages copy-on-write (registry hit, zero
    host round-trip) and still emits bit-identical tokens; a suffix
    diverging mid-page forks privately (counted)."""
    shared = [9, 10, 11, 12, 13, 14, 15, 16, 17]
    prompts = [shared + [i + 1] for i in range(3)]

    def run(layout):
        eng = _engine(layout, fns_key="cow", prefill_chunk=8)
        try:
            out = [
                eng.submit(p, 6, logprobs=True).result(timeout=300)
                for p in prompts
            ]
            st = eng.stats()
        finally:
            _close(eng)
        return [(r["ids"], r["logprobs"]) for r in out], st

    dense, _ = run("dense")
    paged, st = run("paged")
    assert paged == dense
    kp = st["kv_pool"]
    assert kp["registry_hits"] == 2          # requests 2 and 3
    assert st["kv_registry_hit_tokens"] > 0
    assert kp["shared_mappings"] >= 2
    # the prompts diverge inside the second page -> every hit forks it
    assert kp["cow_forks"] == 2


def test_elastic_scaling_grows_and_shrinks():
    """With a 1-slot floor and page headroom, queued traffic grows the
    live slot count (outputs identical to a wide dense engine), and
    the pool shrinks back to the floor at quiesce."""
    gen = np.random.RandomState(3)
    prompts = [gen.randint(1, 64, size=10).tolist() for _ in range(5)]

    def run(layout, slots, **kw):
        eng = _engine(layout, slots=slots, prefill_chunk=8, **kw)
        try:
            futs = [eng.submit(p, 6, logprobs=True) for p in prompts]
            out = [f.result(timeout=300) for f in futs]
            st = eng.stats()
            if layout == "paged":
                # quiesce: the loop shrinks back to the floor at an
                # idle boundary (give it a few)
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < 10:
                    if len(eng._host) == eng._slots_floor:
                        break
                    time.sleep(0.05)
                assert len(eng._host) == eng._slots_floor
                eng._pool.check_invariants()
        finally:
            _close(eng)
        return [(r["ids"], r["logprobs"]) for r in out], st

    dense, _ = run("dense", slots=4)
    paged, st = run("paged", slots=1, max_slots=4,
                    kv_pages=RESERVED_PAGES + 64)
    assert paged == dense
    assert st["slots_scaled"] >= 2           # grew 1 -> 2 -> 4
    assert st["max_slots"] == 4


def test_admission_defers_then_completes_when_pages_free():
    """Lazy-admission deferral: the gate budgets INITIAL pages
    (prefill + one dispatch of lookahead), so a second request whose
    initial need exceeds what the first leaves free DEFERS at the
    boundary (no fail, FIFO preserved) and completes after the first
    retires — and the first can still grow its lazily-deferred decode
    pages while it is alone.  Zero leaks at quiesce."""
    # B fills its 16-bucket (15 real tokens -> 1 pad slot): its initial
    # need alone exceeds what remains while A (worst case smaller but
    # admitted first) is live in a floor-sized pool
    ids_b15 = [7, 3, 44, 5, 6, 9, 2, 41, 8, 30, 31, 32, 33, 34, 35]
    eng = _engine("paged", slots=2, prefill_chunk=8, max_slots=2)
    one_max = eng._layout.max_pages  # constructor floor: 1 worst case
    need_a = eng._pages_worst({"ids": IDS_A, "n_new": 6})
    need_b0 = eng._pages_initial({"ids": ids_b15, "n_new": 6})
    _close(eng)
    pool_pages = max(need_a, one_max)
    assert need_b0 > pool_pages - need_a  # geometry: B must defer
    eng = _engine("paged", slots=2, prefill_chunk=8, max_slots=2,
                  kv_pages=RESERVED_PAGES + pool_pages)
    try:
        f1 = eng.submit(IDS_A, 6)
        f2 = eng.submit(ids_b15, 6)
        r1 = f1.result(timeout=300)
        r2 = f2.result(timeout=300)
        assert len(r1["ids"]) == 6 and len(r2["ids"]) == 6
        assert eng.stats()["kv_decode_page_failures"] == 0
        pool = eng._pool
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            pool.reclaim_all()
            if pool.alloc.free_pages == pool.alloc.total_pages:
                break
            time.sleep(0.05)
        assert pool.alloc.free_pages == pool.alloc.total_pages
        pool.check_invariants()
    finally:
        _close(eng)


def test_request_larger_than_pool_fails_typed():
    """The admission gate's defensive bound: a head request whose
    worst-case page need exceeds the WHOLE pool fails typed
    (NoFreePages) instead of deferring forever.  Unreachable through a
    validated constructor today (kv_pages must hold one worst case),
    so the gate is driven directly on a parked loop."""
    from concurrent.futures import Future

    from mlcomp_tpu.engine import _POISON

    eng = _engine("paged", slots=2, prefill_chunk=8)
    try:
        eng._stop.set()
        eng._queue.put(_POISON)
        eng._thread.join(timeout=30)
        fut = Future()
        eng._pending.append({
            "ids": IDS_A, "n_new": 6, "future": fut, "stream": None,
            "rid": 0,
        })
        eng._pages_worst = lambda r: eng._pool.alloc.total_pages + 1
        assert eng._pop_admittable() is None
        assert not eng._pending  # popped, not left to spin
        with pytest.raises(NoFreePages):
            fut.result(timeout=10)
    finally:
        _close(eng)


def test_churn_no_page_leaks():
    """Staggered mixed-length traffic through admissions, finishes,
    and a mid-stream cancel: at quiesce (registry flushed) the pool is
    fully free and every ref-count invariant holds."""
    gen = np.random.RandomState(7)
    eng = _engine("paged", slots=2, max_slots=4, prefill_chunk=8,
                  kv_pages=RESERVED_PAGES + 48)
    try:
        futs = []
        for i in range(10):
            n = int(gen.randint(1, 15))
            futs.append(eng.submit(
                gen.randint(1, 64, size=n).tolist(),
                int(gen.randint(1, 8)),
            ))
        # cancel one mid-flight: the deadline/cancel retirement path
        # must release its pages like a natural finish
        eng.cancel(futs[5].rid)
        done = 0
        for f in futs:
            try:
                f.result(timeout=300)
                done += 1
            except Exception:
                pass
        assert done >= 9
        pool = eng._pool
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            pool.reclaim_all()
            if pool.alloc.free_pages == pool.alloc.total_pages:
                break
            time.sleep(0.05)
        st = pool.stats()
        assert st["pages_free"] == st["pages_total"], st
        assert st["outstanding_page_leases"] == 0
        pool.check_invariants()
    finally:
        _close(eng)


def test_construction_validation():
    model, params = _model_and_params(False)
    with pytest.raises(ValueError, match="kv_layout"):
        _engine("dense", kv_layout="paged123")
    with pytest.raises(ValueError, match="max_slots"):
        _engine("dense", max_slots=8)
    with pytest.raises(ValueError, match="kv_page_tokens"):
        _engine("dense", kv_pages=64)
    with pytest.raises(ValueError, match="divide"):
        _engine("paged", kv_page_tokens=3)
    with pytest.raises(ValueError, match="below slots"):
        _engine("paged", slots=4, max_slots=2)
    with pytest.raises(ValueError, match="worst-case"):
        _engine("paged", kv_pages=RESERVED_PAGES + 1)
    svc_err = pytest.raises(ValueError, match="continuous")
    with svc_err:
        GenerationService(model, {"params": params}, batcher="window",
                          prompt_buckets=(16,), kv_layout="paged")


def test_fatblock_recheck_at_scale():
    """The _GEMV_ROWS cliff is re-derived when elastic slots grow (the
    constructor only priced the floor)."""
    from mlcomp_tpu.ops.pallas.quant_matmul import _GEMV_ROWS

    eng = _engine("paged", slots=2, max_slots=256)
    try:
        eng.quant_kernel = True  # the check's only input besides width
        with pytest.warns(UserWarning, match="fat-block"):
            eng._check_scale_fatblock(_GEMV_ROWS + 1)
        # once per engine: the second grow past the cliff stays quiet
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            eng._check_scale_fatblock(_GEMV_ROWS + 2)
    finally:
        eng.quant_kernel = False
        _close(eng)


def test_serve_rejects_no_free_pages_with_page_rate_retry():
    """Admission control on the paged layout: a flood past the page
    budget fast-fails with reason ``no_free_pages`` and a Retry-After
    from the projected page-free rate; accepted requests all finish."""
    model, params = _model_and_params(False)
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,), prefill_chunk=8,
        kv_layout="paged", max_slots=4,
    )
    try:
        gen = np.random.RandomState(1)
        futs, rejects = [], 0
        for _ in range(12):
            try:
                futs.append(svc.submit(
                    gen.randint(1, 64, size=10).tolist(), 8
                ))
            except BackpressureError as e:
                rejects += 1
                assert e.reason == "no_free_pages"
                assert 1.0 <= e.retry_after_s <= 60.0
        assert futs and rejects  # bounded: some in, some 429
        for f in futs:
            assert len(f.result(timeout=300)["ids"]) == 8
        st = svc.stats()
        assert st["rejected"]["no_free_pages"] == rejects
        assert st["kv_pool"]["pages_total"] > 0  # top-level lift
    finally:
        svc.close()

"""Speculative batcher (serve.py batcher='speculative'): greedy parity
with direct generate, eos/budget trimming, knob validation, stats."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import GenerationService
from mlcomp_tpu.train.state import init_model


def _service(**kw):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, mstate = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    kw.setdefault("batch_sizes", (1, 2, 4))
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("max_new_buckets", (4, 8))
    kw.setdefault("batcher", "speculative")
    return model, GenerationService(model, {"params": params, **mstate}, **kw)


def test_spec_batcher_matches_direct_generate():
    model, svc = _service(spec_k=3)
    try:
        prompt = [3, 14, 15, 9, 2]  # length 5 -> bucket 8, left-padded
        got = svc.generate(prompt, max_new_tokens=4)
        direct = generate(
            model, svc.variables, jnp.asarray([prompt], jnp.int32), 4
        )
        expect = np.asarray(direct)[0, len(prompt):].tolist()
        assert got["ids"] == expect, (got, expect)
        assert got["batched_with"] == 1
        st = svc.stats()
        assert st["batcher"] == "speculative"
        assert st["spec_forwards"] >= 1
        # the device ran the full 4-token bucket; emitted >= trimmed len
        assert st["spec_tokens"] >= len(got["ids"])
    finally:
        svc.close()


def test_spec_batcher_eos_trims_like_window():
    model, svc = _service(spec_k=4)
    try:
        prompt = [5, 9, 22]
        free = svc.generate(prompt, max_new_tokens=8)["ids"]
        assert len(free) == 8
        eos = free[3]
        got = svc.generate(prompt, max_new_tokens=8, eos_id=eos)["ids"]
        assert got == free[: free.index(eos) + 1]
    finally:
        svc.close()


def test_spec_batcher_rejects_sampling_knobs():
    _, svc = _service()
    try:
        with pytest.raises(ValueError, match="greedy-only"):
            svc.generate([1, 2], max_new_tokens=4, temperature=0.7)
        with pytest.raises(ValueError, match="repetition_penalty"):
            svc.generate([1, 2], max_new_tokens=4, repetition_penalty=1.2)
        with pytest.raises(ValueError, match="logprobs"):
            svc.generate([1, 2], max_new_tokens=4, logprobs=True)
        import queue as _q

        with pytest.raises(ValueError, match="streaming"):
            svc.submit([1, 2], 4, stream=_q.Queue()).result(timeout=10)
    finally:
        svc.close()


def test_spec_batcher_service_constraints():
    with pytest.raises(ValueError, match="greedy-only"):
        _service(temperature=0.5)
    with pytest.raises(ValueError, match="spec_k"):
        _service(spec_k=0)


def test_engine_spec_service_matches_window_reference():
    """engine_spec_k on the continuous batcher: batched speculative
    decoding behind the normal service API, greedy-equal to the window
    batcher on the same weights."""
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, mstate = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    variables = {"params": params, **mstate}
    kw = dict(batch_sizes=(1, 2), prompt_buckets=(8, 16),
              max_new_buckets=(4, 8))
    svc = GenerationService(model, variables, batcher="continuous",
                            engine_spec_k=3, **kw)
    ref = GenerationService(model, variables, batcher="window", **kw)
    try:
        rs = np.random.RandomState(4)
        for n in (5, 9):
            p = rs.randint(1, 64, n).tolist()
            got = svc.generate(p, max_new_tokens=6)
            want = ref.generate(p, max_new_tokens=6)
            assert got["ids"] == want["ids"], p
        with pytest.raises(ValueError, match="greedy-only"):
            svc.generate([1, 2], max_new_tokens=4, temperature=0.9)
    finally:
        svc.close()
        ref.close()
    with pytest.raises(ValueError, match="continuous"):
        GenerationService(model, variables, batcher="window",
                          engine_spec_k=2, **kw)
    with pytest.raises(ValueError, match="greedy-only"):
        GenerationService(model, variables, batcher="continuous",
                          engine_spec_k=2, temperature=0.7, **kw)


def test_spec_batcher_warmup_and_concurrent_requests():
    _, svc = _service(spec_k=2)
    try:
        n = svc.warmup()
        assert n == 4  # 2 prompt buckets x 2 new buckets
        futs = [svc.submit([i + 1, i + 2], 4) for i in range(6)]
        outs = [f.result(timeout=120) for f in futs]
        assert all(len(o["ids"]) == 4 for o in outs)
        # identical prompts -> identical greedy outputs, whatever the
        # arrival interleaving (B=1: no cross-request contamination)
        again = svc.generate([1, 2], max_new_tokens=4)
        assert again["ids"] == outs[0]["ids"]
    finally:
        svc.close()

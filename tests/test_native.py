"""Native C++ data-ops: build, bind, and match numpy semantics."""

import numpy as np
import pytest

from mlcomp_tpu import native


@pytest.fixture(scope="module")
def built():
    lib = native.lib()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_gather_matches_numpy(built):
    src = np.random.RandomState(0).rand(64, 7, 3).astype(np.float32)
    idx = np.random.RandomState(1).randint(0, 64, size=32)
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_multithreaded_large(built):
    src = np.random.RandomState(2).rand(512, 1024).astype(np.float32)  # > 1 MiB
    idx = np.random.RandomState(3).permutation(512)
    out = native.gather_rows(src, idx, n_threads=4)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_int_dtype_and_1d(built):
    src = np.arange(100, dtype=np.int32)
    idx = np.array([5, 2, 99, 0])
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_shuffle_is_permutation_and_deterministic(built):
    a = native.shuffled_indices(1000, seed=7)
    b = native.shuffled_indices(1000, seed=7)
    c = native.shuffled_indices(1000, seed=8)
    assert sorted(a.tolist()) == list(range(1000))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_loader_uses_native_gather(built):
    from mlcomp_tpu.data.loader import DataLoader

    data = {"x": np.random.RandomState(4).rand(40, 5).astype(np.float32),
            "y": np.arange(40, dtype=np.int32)}
    dl = DataLoader(data, batch_size=16, shuffle=True, seed=1, mesh=None)
    seen = []
    for batch in dl:
        assert batch["x"].shape == (16, 5)
        seen.extend(np.asarray(batch["y"]).tolist())
    # rows come from the dataset, shuffled, no duplicates within epoch
    assert len(seen) == 32 and len(set(seen)) == 32


def _random_dag_case(rng, n):
    """Random DAG (edges only point backward) + random statuses."""
    from mlcomp_tpu.dag.schema import ResourceSpec, TaskSpec, TaskStatus

    tasks = []
    for i in range(n):
        k = rng.integers(0, min(i, 3) + 1)
        deps = rng.choice(i, size=k, replace=False) if i and k else []
        tasks.append(
            TaskSpec(
                name=f"t{i}",
                executor="noop",
                depends=tuple(f"t{int(d)}" for d in deps),
                resources=ResourceSpec(priority=int(rng.integers(0, 5))),
            )
        )
    pool = [
        TaskStatus.NOT_RAN, TaskStatus.QUEUED, TaskStatus.IN_PROGRESS,
        TaskStatus.SUCCESS, TaskStatus.FAILED, TaskStatus.SKIPPED,
        TaskStatus.STOPPED,
    ]
    statuses = {t.name: pool[int(rng.integers(0, len(pool)))] for t in tasks}
    return tasks, statuses


def test_dag_analyze_matches_python_walk(built):
    """Property test: native one-pass analysis == Python ready/doomed walk."""
    from mlcomp_tpu.dag.graph import DagAnalyzer, doomed_tasks, ready_tasks

    rng = np.random.default_rng(0)
    for trial in range(25):
        tasks, statuses = _random_dag_case(rng, int(rng.integers(1, 40)))
        analyzer = DagAnalyzer(tasks)
        ready, doomed = analyzer.analyze(statuses)
        py_ready = {t.name for t in ready_tasks(tasks, statuses)}
        py_doomed = doomed_tasks(tasks, statuses)
        assert {t.name for t in ready} == py_ready, trial
        assert doomed == py_doomed, trial
        # ready ordering: priority strictly descending
        prios = [t.resources.priority for t in ready]
        assert prios == sorted(prios, reverse=True), (trial, prios)


def test_dag_analyze_priority_order(built):
    from mlcomp_tpu.dag.schema import ResourceSpec, TaskSpec, TaskStatus
    from mlcomp_tpu.dag.graph import DagAnalyzer

    tasks = [
        TaskSpec(name="lo", executor="noop", resources=ResourceSpec(priority=1)),
        TaskSpec(name="hi", executor="noop", resources=ResourceSpec(priority=9)),
        TaskSpec(name="mid", executor="noop", resources=ResourceSpec(priority=5)),
    ]
    ready, doomed = DagAnalyzer(tasks).analyze(
        {t.name: TaskStatus.NOT_RAN for t in tasks}
    )
    assert [t.name for t in ready] == ["hi", "mid", "lo"] and not doomed


def test_dag_analyze_doom_propagates_transitively(built):
    from mlcomp_tpu.dag.schema import TaskSpec, TaskStatus
    from mlcomp_tpu.dag.graph import DagAnalyzer

    tasks = [
        TaskSpec(name="a", executor="noop"),
        TaskSpec(name="b", executor="noop", depends=("a",)),
        TaskSpec(name="c", executor="noop", depends=("b",)),
        TaskSpec(name="d", executor="noop", depends=("c",)),
    ]
    ready, doomed = DagAnalyzer(tasks).analyze(
        {"a": TaskStatus.FAILED, "b": TaskStatus.NOT_RAN,
         "c": TaskStatus.NOT_RAN, "d": TaskStatus.NOT_RAN}
    )
    assert doomed == {"b", "c", "d"} and not ready


def test_dag_analyze_native_actually_engaged(built):
    """The native path (not the fallback) is what runs when the lib built."""
    lib = native.lib()
    assert hasattr(lib, "mlc_dag_analyze")
    res = native.dag_analyze(
        np.array([0, 0, 1]), np.array([0]), np.array([2, 0], dtype=np.int8),
        np.array([0, 0]),
    )
    assert res is not None
    ready, doomed = res
    assert ready.tolist() == [1] and doomed.tolist() == []

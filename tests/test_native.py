"""Native C++ data-ops: build, bind, and match numpy semantics."""

import numpy as np
import pytest

from mlcomp_tpu import native


@pytest.fixture(scope="module")
def built():
    lib = native.lib()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_gather_matches_numpy(built):
    src = np.random.RandomState(0).rand(64, 7, 3).astype(np.float32)
    idx = np.random.RandomState(1).randint(0, 64, size=32)
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_multithreaded_large(built):
    src = np.random.RandomState(2).rand(512, 1024).astype(np.float32)  # > 1 MiB
    idx = np.random.RandomState(3).permutation(512)
    out = native.gather_rows(src, idx, n_threads=4)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_int_dtype_and_1d(built):
    src = np.arange(100, dtype=np.int32)
    idx = np.array([5, 2, 99, 0])
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_shuffle_is_permutation_and_deterministic(built):
    a = native.shuffled_indices(1000, seed=7)
    b = native.shuffled_indices(1000, seed=7)
    c = native.shuffled_indices(1000, seed=8)
    assert sorted(a.tolist()) == list(range(1000))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_loader_uses_native_gather(built):
    from mlcomp_tpu.data.loader import DataLoader

    data = {"x": np.random.RandomState(4).rand(40, 5).astype(np.float32),
            "y": np.arange(40, dtype=np.int32)}
    dl = DataLoader(data, batch_size=16, shuffle=True, seed=1, mesh=None)
    seen = []
    for batch in dl:
        assert batch["x"].shape == (16, 5)
        seen.extend(np.asarray(batch["y"]).tolist())
    # rows come from the dataset, shuffled, no duplicates within epoch
    assert len(seen) == 32 and len(set(seen)) == 32

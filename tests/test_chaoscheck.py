"""Tier-1 wiring of tools/chaoscheck.py: the serving resilience
contract — each injected fault (dispatch exception, wedged dispatch,
cache lookup/capture raise) recovers to a healthy daemon with no hung
futures, no slot/pin leaks, and bit-identical token streams for
surviving traffic — checked against a live toy daemon, like
test_cachecheck.py wires the prefix index's fault harness and
test_obs_check.py the observability contract."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import chaoscheck  # noqa: E402
from mlcomp_tpu.utils import faults  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


def test_chaoscheck_end_to_end():
    out = chaoscheck.run()
    # every scenario must have actually run AND recovered
    assert out["slow_resolve"] == "exact"
    assert out["dispatch_exception"]["recovered"]
    assert out["dispatch_stall"]["saw_503"]
    # the watchdog beat the 2.5 s wedge (bounded failure, not a hang)
    assert out["dispatch_stall"]["failed_in_s"] < 2.4
    assert out["cache_lookup_raise"] == "bypassed_exact"
    assert out["cache_capture_raise"] == "contained"
    # a fault inside the fused admission path failed ONLY the admitting
    # request: the streaming survivor stayed bit-identical and later
    # admissions fused again
    assert out["fused_prefill_raise"]["survivor_exact"]
    wd = out["final_health"]["watchdog"]
    # the fused fault is admission-scoped: no extra stalls or restarts
    assert wd["stalls"] == 1 and wd["restarts"] == 2
    # fleet: one replica of a two-replica fleet killed mid-stream —
    # the router marked it down within the health-poll bound, the
    # surviving stream stayed bit-identical, the manager restarted it
    # within the budget, and its affinity keys came home
    rk = out["replica_kill"]
    assert rk["survivor_exact"] and rk["rejoined"]
    assert rk["restarts"] >= 1
    assert rk["marked_down_in_s"] < 10
    # disaggregation: a prefill replica killed mid-transfer — the
    # router retried the short-read hop on the survivor (client saw
    # one exact 200), the decode side rejected the partial blob typed
    # with zero pages/leases touched, and both sides drained clean
    pk = out["prefill_kill_mid_transfer"]
    assert pk["kills"] >= 1 and pk["retried_via_survivor"]
    assert pk["import_reject"] == "typed_400_bad_handoff"
    assert pk["leaked_pages"] == 0

"""Helper for retry tests: fails on first call, succeeds after (file-marked)."""

from pathlib import Path


def fail_once(ctx=None, marker: str = ""):
    p = Path(marker)
    attempts = p.read_text() if p.exists() else ""
    p.write_text(attempts + "1")
    if len(attempts) == 0:
        raise RuntimeError("first attempt always fails")
    return {"attempts": len(attempts) + 1}

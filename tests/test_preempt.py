"""Preemption: SIGTERM → between-steps checkpoint → no-retry requeue →
resumed completion."""

import jax
import numpy as np
import pytest

from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.worker import Worker
from mlcomp_tpu.utils import preempt


@pytest.fixture(autouse=True)
def _clear_flag():
    preempt.clear()
    yield
    preempt.clear()


def test_trainer_raises_between_steps():
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "mlp", "hidden": [16], "num_classes": 4},
        "optimizer": {"name": "sgd", "lr": 0.1},
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": 1,
        "data": {"train": {"name": "synthetic_classification", "n": 64,
                           "dim": 8, "num_classes": 4, "batch_size": 16}},
    }
    tr = Trainer(cfg)
    preempt.request_preemption()
    with pytest.raises(preempt.TaskPreempted, match="step 0"):
        tr.train_epoch()
    preempt.clear()
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def _train_dag(store, tmp_path, epochs=2, **extra):
    args = {
        "model": {"name": "mlp", "hidden": [16], "num_classes": 4},
        "optimizer": {"name": "sgd", "lr": 0.1},
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": epochs,
        "data": {"train": {"name": "synthetic_classification", "n": 64,
                           "dim": 8, "num_classes": 4, "batch_size": 16}},
        "project": "t",
        "dag_name": "pre",
        **extra,
    }
    dag = DagSpec(
        name="pre", project="t",
        tasks=(TaskSpec(name="train", executor="train", args=args,
                        max_retries=0),),
    )
    dag_id = store.submit_dag(dag)
    store.set_task_status(dag_id, ["train"], TaskStatus.QUEUED)
    return dag_id, store.task_rows(dag_id)[0]["id"]


def test_preempted_train_requeues_free_and_resumes(tmp_path, tmp_db,
                                                   monkeypatch):
    """max_retries=0 train task: a preemption mid-run checkpoints,
    requeues WITHOUT consuming a retry, and the second attempt resumes
    from the checkpoint and succeeds."""
    monkeypatch.setenv("MLCOMP_TPU_STORAGE", str(tmp_path / "storage"))
    store = Store(tmp_db)
    try:
        _, tid = _train_dag(store, tmp_path)
        w = Worker(store, name="pw", workdir=str(tmp_path / "wk"))

        preempt.request_preemption()  # fires at the first step check
        assert w.run_once() is True
        row = store.task_row(tid)
        assert row["status"] == TaskStatus.QUEUED.value, row["error"]
        assert row["retries"] == 0
        assert row["infra_requeues"] == 1
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert "task preempted" in logs and "checkpoint saved" in logs

        preempt.clear()
        assert w.run_once() is True
        row = store.task_row(tid)
        assert row["status"] == TaskStatus.SUCCESS.value, row["error"]
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert "resumed from checkpoint" in logs or "restored" in logs
    finally:
        store.close()


def test_sigterm_to_isolated_child_preempts(tmp_path, tmp_db, monkeypatch):
    """The REAL delivery path: an isolated task child gets SIGTERM (what
    a spot reclaim or pool drain sends); the in-child handler flags, the
    train loop checkpoints, and the task requeues without consuming its
    (zero) retry budget, then completes on the next attempt."""
    import os
    import signal
    import time

    monkeypatch.setenv("MLCOMP_TPU_STORAGE", str(tmp_path / "storage"))
    store = Store(tmp_db)
    try:
        _, tid = _train_dag(
            store, tmp_path, epochs=2000, ckpt_every=500,
            # meaty enough that 2000 epochs take minutes on one CPU core:
            # the SIGTERM must land mid-training, not after completion.
            # dp=1 keeps cross-device collectives out of the child — the
            # 8-virtual-devices-on-one-core rendezvous can fatally time
            # out under load, which is an environment flake, not the
            # behavior under test
            model={"name": "mlp", "hidden": [512, 512], "num_classes": 4},
            data={"train": {"name": "synthetic_classification", "n": 4096,
                            "dim": 256, "num_classes": 4,
                            "batch_size": 32}},
        )
        w = Worker(
            store, name="pw", workdir=str(tmp_path / "wk"), isolate=True,
            # one virtual device in the child: no cross-device collectives
            # (the 8-on-one-core rendezvous can fatally time out under
            # load — an environment flake, not the behavior under test)
            child_env={
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1"
            },
        )
        # claim + spawn the child without blocking on completion
        deadline = time.time() + 120
        while not w._children and time.time() < deadline:
            w.poll()
            time.sleep(0.2)
        assert w._children, "child never spawned"
        child = w._children[0]
        # wait for training to actually start (first epoch metric)
        deadline = time.time() + 240
        while time.time() < deadline:
            if any("epoch 0" in l["message"] for l in store.task_logs(tid)):
                break
            time.sleep(0.5)
        os.kill(child["proc"].pid, signal.SIGTERM)
        # wait for the CHILD to exit before any worker poll: poll() would
        # requeue AND immediately respawn in one call, racing the args
        # edit below (the retry must run with lowered epochs)
        child["proc"].wait(timeout=180)
        # lower the bar so the resumed attempt finishes quickly
        import json as _json

        with store._tx() as c:
            args = _json.loads(store.task_row(tid)["args"])
            args["epochs"] = 1
            c.execute("UPDATE tasks SET args=? WHERE id=?",
                      (_json.dumps(args), tid))
        w.poll()  # reap -> marker classification -> free requeue
        row = store.task_row(tid)
        assert row["retries"] == 0, row["error"]
        assert row["infra_requeues"] == 1, (row["status"], row["error"])
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert "preempted at step" in logs and "checkpoint saved" in logs
        deadline = time.time() + 240
        while time.time() < deadline:
            w.poll()
            row = store.task_row(tid)
            if row["status"] in (TaskStatus.SUCCESS.value,
                                 TaskStatus.FAILED.value):
                break
            time.sleep(0.3)
        assert row["status"] == TaskStatus.SUCCESS.value, row["error"]
        logs = "\n".join(l["message"] for l in store.task_logs(tid))
        assert "resumed from checkpoint" in logs
    finally:
        store.close()


def test_preemption_cap_falls_back_to_retry_budget(tmp_path, tmp_db,
                                                   monkeypatch):
    """After 3 free requeues the normal (exhausted) retry budget applies:
    the task fails instead of looping forever."""
    monkeypatch.setenv("MLCOMP_TPU_STORAGE", str(tmp_path / "storage"))
    store = Store(tmp_db)
    try:
        _, tid = _train_dag(store, tmp_path)
        w = Worker(store, name="pw", workdir=str(tmp_path / "wk"))
        for i in range(3):
            preempt.request_preemption()
            assert w.run_once() is True
            row = store.task_row(tid)
            assert row["status"] == TaskStatus.QUEUED.value
            assert row["infra_requeues"] == i + 1
        preempt.request_preemption()
        assert w.run_once() is True
        row = store.task_row(tid)
        assert row["status"] == TaskStatus.FAILED.value  # max_retries=0
    finally:
        store.close()

"""Pipelined decoder LM: schedule parity, sharding, Trainer integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    make_mesh,
    replicated,
    set_current_mesh,
)


def _model(**over):
    cfg = {
        "name": "transformer_lm_pp",
        "vocab_size": 64,
        "hidden": 32,
        "layers": 8,
        "heads": 4,
        "kv_heads": 2,
        "mlp_dim": 64,
        "dtype": "float32",
    }
    cfg.update(over)
    return create_model(cfg)


def test_pipelined_matches_sequential_schedule():
    """Same params through the pp=4 ring == the scan reference path."""
    model = _model()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 16)), jnp.int32)

    seq_mesh = make_mesh(MeshSpec(dp=8))
    set_current_mesh(seq_mesh)
    variables = model.init(jax.random.PRNGKey(0), ids)
    ref = jax.jit(model.apply)(variables, ids)

    pp_mesh = make_mesh(MeshSpec(dp=2, pp=4))
    set_current_mesh(pp_mesh)
    try:
        v = jax.device_put(variables, replicated(pp_mesh))
        x = jax.device_put(ids, batch_sharding(pp_mesh))
        out = jax.jit(model.apply)(v, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
        )
    finally:
        set_current_mesh(None)


def test_pipelined_interleaved_layers_match():
    """layers=8 on pp=4 → v=2 interleaved laps; numerics must hold."""
    model = _model(layers=8, n_microbatches=4)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 64, (8, 8)), jnp.int32)
    seq_mesh = make_mesh(MeshSpec(dp=8))
    set_current_mesh(seq_mesh)
    variables = model.init(jax.random.PRNGKey(1), ids)
    ref = jax.jit(model.apply)(variables, ids)
    pp_mesh = make_mesh(MeshSpec(dp=2, pp=4))
    set_current_mesh(pp_mesh)
    try:
        out = jax.jit(model.apply)(
            jax.device_put(variables, replicated(pp_mesh)),
            jax.device_put(ids, batch_sharding(pp_mesh)),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
        )
    finally:
        set_current_mesh(None)


def test_trainer_trains_pipelined_lm():
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {
            "name": "transformer_lm_pp",
            "vocab_size": 64,
            "hidden": 32,
            "layers": 4,
            "heads": 4,
            "mlp_dim": 64,
            "dtype": "float32",
        },
        "optimizer": {"name": "adam", "lr": 1e-3},
        "loss": "lm_cross_entropy",
        "metrics": [],
        "epochs": 1,
        "seed": 0,
        "mesh": {"dp": 2, "pp": 4},
        "data": {
            "train": {
                "name": "synthetic_tokens",
                "n": 16,
                "seq_len": 16,
                "vocab_size": 64,
                "batch_size": 8,
            }
        },
    }
    try:
        tr = Trainer(cfg)
        # stacked stage weights must be sharded over pp
        q = tr.state.params["stages_q"]
        assert q.shape[0] == 4
        assert "pp" in q.sharding.spec
        first = tr.train_epoch()
        assert np.isfinite(first["loss"])
        second = tr.train_epoch()
        assert second["loss"] < first["loss"]  # it actually learns
    finally:
        set_current_mesh(None)


def test_device_ordered_layout_matches_network_order():
    """device_ordered_pp=4 stores stacks permutation-free: applying the
    device-ordered model to interleave_stage_params(network params) must
    equal the network-ordered model on the same mesh, and the sequential
    fallback must un-permute correctly."""
    from mlcomp_tpu.parallel.pipeline import interleave_stage_params

    net = _model(layers=8, n_microbatches=4)
    dev = _model(layers=8, n_microbatches=4, device_ordered_pp=4)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (8, 8)), jnp.int32)

    seq_mesh = make_mesh(MeshSpec(dp=8))
    set_current_mesh(seq_mesh)
    variables = net.init(jax.random.PRNGKey(2), ids)
    ref = jax.jit(net.apply)(variables, ids)

    stages = {k: v for k, v in variables["params"].items()
              if k.startswith("stages_")}
    rest = {k: v for k, v in variables["params"].items()
            if not k.startswith("stages_")}
    dev_vars = {"params": {**rest, **interleave_stage_params(stages, 4)}}

    # sequential fallback path (no pp axis) de-interleaves internally
    out_seq = jax.jit(dev.apply)(dev_vars, ids)
    np.testing.assert_allclose(
        np.asarray(out_seq), np.asarray(ref), atol=2e-4, rtol=2e-4
    )

    pp_mesh = make_mesh(MeshSpec(dp=2, pp=4))
    set_current_mesh(pp_mesh)
    try:
        out_pp = jax.jit(dev.apply)(
            jax.device_put(dev_vars, replicated(pp_mesh)),
            jax.device_put(ids, batch_sharding(pp_mesh)),
        )
        np.testing.assert_allclose(
            np.asarray(out_pp), np.asarray(ref), atol=2e-4, rtol=2e-4
        )
        # wrong-pp application must refuse, not mis-order layers
        bad_mesh = make_mesh(MeshSpec(dp=4, pp=2))
        set_current_mesh(bad_mesh)
        with pytest.raises(ValueError, match="device-ordered"):
            dev.apply(
                jax.device_put(dev_vars, replicated(bad_mesh)),
                jax.device_put(ids, batch_sharding(bad_mesh)),
            )
    finally:
        set_current_mesh(None)


def test_pipelined_rejects_indivisible_layers():
    model = _model(layers=6)
    ids = jnp.zeros((4, 8), jnp.int32)
    mesh = make_mesh(MeshSpec(dp=2, pp=4))
    set_current_mesh(mesh)
    try:
        with pytest.raises(ValueError, match="not a multiple"):
            model.init(jax.random.PRNGKey(0), ids)
    finally:
        set_current_mesh(None)

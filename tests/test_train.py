import jax
import numpy as np
import pytest

from mlcomp_tpu.train.loop import Trainer


def mlp_cfg(**over):
    cfg = {
        "model": {"name": "mlp", "num_classes": 4, "hidden": [32]},
        "optimizer": {"name": "adam", "lr": 1e-2},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "epochs": 3,
        "data": {
            "train": {
                "name": "synthetic_classification",
                "n": 256,
                "num_classes": 4,
                "dim": 16,
                "batch_size": 64,
            },
            "valid": {
                "name": "synthetic_classification",
                "n": 128,
                "num_classes": 4,
                "dim": 16,
                "seed": 1,
                "batch_size": 64,
            },
        },
    }
    cfg.update(over)
    return cfg


def test_trainer_learns():
    tr = Trainer(mlp_cfg())
    first = tr.train_epoch()
    for _ in range(2):
        last = tr.train_epoch()
    assert last["loss"] < first["loss"]
    val = tr.eval_epoch()
    assert val["accuracy"] > 0.8  # blobs are nearly separable


def test_trainer_uses_all_devices():
    tr = Trainer(mlp_cfg())
    # default mesh: dp = all 8 virtual devices
    assert tr.mesh.devices.size == len(jax.devices())
    # params replicated across the whole mesh
    leaf = jax.tree.leaves(tr.state.params)[0]
    assert leaf.sharding.is_fully_replicated
    assert int(tr.state.step) == 0


def test_predict_keeps_tail_without_drop_last():
    cfg = mlp_cfg()
    cfg["data"]["infer"] = {
        "name": "synthetic_classification",
        "n": 100,  # not divisible by 32
        "num_classes": 4,
        "dim": 16,
        "batch_size": 32,
        "drop_last": False,
    }
    tr = Trainer(cfg)
    assert tr.predict("infer").shape == (100, 4)


def test_fit_resume_runs_remaining_epochs():
    cfg = mlp_cfg()
    tr = Trainer(cfg)
    seen = []
    tr.fit(on_epoch=lambda e, s: seen.append(e))
    assert seen == [0, 1, 2]
    assert tr.epochs_done == 3
    # simulate a restart that restored the same state: nothing left to run
    seen2 = []
    tr.fit(on_epoch=lambda e, s: seen2.append(e))
    assert seen2 == []
    # extend the budget: continues from epoch 3, not from 0
    tr.epochs = 4
    seen3 = []
    tr.fit(on_epoch=lambda e, s: seen3.append(e))
    assert seen3 == [3]


def test_batchnorm_model_state():
    cfg = mlp_cfg()
    cfg["model"] = {"name": "mnist_cnn", "num_classes": 10, "features": [8], "dense": 16}
    cfg["data"] = {
        "train": {"name": "synth_mnist", "n": 64, "batch_size": 32},
    }
    cfg["epochs"] = 1
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_predict_shapes():
    cfg = mlp_cfg()
    cfg["data"]["infer"] = {
        "name": "synthetic_classification",
        "n": 128,
        "num_classes": 4,
        "dim": 16,
        "batch_size": 64,
    }
    tr = Trainer(cfg)
    preds = tr.predict("infer")
    assert preds.shape == (128, 4)


def test_grad_accum_and_clip():
    cfg = mlp_cfg()
    cfg["optimizer"] = {"name": "sgd", "lr": 0.1, "grad_clip": 1.0, "accum_steps": 2}
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_lr_schedule():
    cfg = mlp_cfg()
    cfg["optimizer"] = {
        "name": "adam",
        "lr": {"name": "warmup_cosine", "lr": 1e-2, "warmup_steps": 4, "decay_steps": 12},
    }
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])

import jax
import numpy as np
import pytest

from mlcomp_tpu.train.loop import Trainer


def mlp_cfg(**over):
    cfg = {
        "model": {"name": "mlp", "num_classes": 4, "hidden": [32]},
        "optimizer": {"name": "adam", "lr": 1e-2},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "epochs": 3,
        "data": {
            "train": {
                "name": "synthetic_classification",
                "n": 256,
                "num_classes": 4,
                "dim": 16,
                "batch_size": 64,
            },
            "valid": {
                "name": "synthetic_classification",
                "n": 128,
                "num_classes": 4,
                "dim": 16,
                "seed": 1,
                "batch_size": 64,
            },
        },
    }
    cfg.update(over)
    return cfg


def test_trainer_learns():
    tr = Trainer(mlp_cfg())
    first = tr.train_epoch()
    for _ in range(2):
        last = tr.train_epoch()
    assert last["loss"] < first["loss"]
    val = tr.eval_epoch()
    assert val["accuracy"] > 0.8  # blobs are nearly separable


def test_trainer_uses_all_devices():
    tr = Trainer(mlp_cfg())
    # default mesh: dp = all 8 virtual devices
    assert tr.mesh.devices.size == len(jax.devices())
    # params replicated across the whole mesh
    leaf = jax.tree.leaves(tr.state.params)[0]
    assert leaf.sharding.is_fully_replicated
    assert int(tr.state.step) == 0


def test_predict_keeps_tail_without_drop_last():
    cfg = mlp_cfg()
    cfg["data"]["infer"] = {
        "name": "synthetic_classification",
        "n": 100,  # not divisible by 32
        "num_classes": 4,
        "dim": 16,
        "batch_size": 32,
        "drop_last": False,
    }
    tr = Trainer(cfg)
    assert tr.predict("infer").shape == (100, 4)


def test_fit_resume_runs_remaining_epochs():
    cfg = mlp_cfg()
    tr = Trainer(cfg)
    seen = []
    tr.fit(on_epoch=lambda e, s: seen.append(e))
    assert seen == [0, 1, 2]
    assert tr.epochs_done == 3
    # simulate a restart that restored the same state: nothing left to run
    seen2 = []
    tr.fit(on_epoch=lambda e, s: seen2.append(e))
    assert seen2 == []
    # extend the budget: continues from epoch 3, not from 0
    tr.epochs = 4
    seen3 = []
    tr.fit(on_epoch=lambda e, s: seen3.append(e))
    assert seen3 == [3]


def test_batchnorm_model_state():
    cfg = mlp_cfg()
    cfg["model"] = {"name": "mnist_cnn", "num_classes": 10, "features": [8], "dense": 16}
    cfg["data"] = {
        "train": {"name": "synth_mnist", "n": 64, "batch_size": 32},
    }
    cfg["epochs"] = 1
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_predict_shapes():
    cfg = mlp_cfg()
    cfg["data"]["infer"] = {
        "name": "synthetic_classification",
        "n": 128,
        "num_classes": 4,
        "dim": 16,
        "batch_size": 64,
    }
    tr = Trainer(cfg)
    preds = tr.predict("infer")
    assert preds.shape == (128, 4)


def test_grad_accum_and_clip():
    cfg = mlp_cfg()
    cfg["optimizer"] = {"name": "sgd", "lr": 0.1, "grad_clip": 1.0, "accum_steps": 2}
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_lr_schedule():
    cfg = mlp_cfg()
    cfg["optimizer"] = {
        "name": "adam",
        "lr": {"name": "warmup_cosine", "lr": 1e-2, "warmup_steps": 4, "decay_steps": 12},
    }
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_cross_entropy_ignores_out_of_range_labels():
    """torch ignore_index semantics: labels outside [0, C) drop out."""
    import jax.numpy as jnp
    from mlcomp_tpu.train.losses import create_loss

    ce = create_loss("cross_entropy")
    logits = jnp.asarray([[2.0, 0.0, 0.0], [0.0, 2.0, 0.0], [0.0, 0.0, 2.0]])
    full = ce(logits[:2], {"y": jnp.asarray([0, 1])})
    with_ignored = ce(logits, {"y": jnp.asarray([0, 1, 9])})
    assert float(full) == pytest.approx(float(with_ignored), rel=1e-6)
    neg = ce(logits, {"y": jnp.asarray([0, 1, -1])})
    assert float(full) == pytest.approx(float(neg), rel=1e-6)


def test_pixel_cross_entropy_ignores_void_pixels():
    import jax.numpy as jnp
    from mlcomp_tpu.train.losses import create_loss

    pce = create_loss("pixel_cross_entropy")
    logits = jnp.zeros((1, 2, 2, 3)).at[..., 0].set(2.0)
    y = jnp.asarray([[[0, 0], [255, -1]]])
    loss = pce(logits, {"y": y})
    y_clean = jnp.asarray([[[0, 0], [0, 0]]])
    loss_clean = pce(logits, {"y": y_clean})
    assert float(loss) == pytest.approx(float(loss_clean), rel=1e-6)


def test_metrics_ignore_out_of_range_labels():
    import jax.numpy as jnp
    from mlcomp_tpu.train.metrics import create_metrics

    acc = create_metrics(["accuracy"])["accuracy"]
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0], [2.0, 0.0]])
    # third label is void: metric must equal the 2-sample accuracy
    full = acc(logits[:2], {"y": jnp.asarray([0, 1])})
    with_void = acc(logits, {"y": jnp.asarray([0, 1, 255])})
    assert float(full) == pytest.approx(float(with_void))

    pacc = create_metrics(["pixel_accuracy"])["pixel_accuracy"]
    out = jnp.zeros((1, 2, 2, 3)).at[..., 0].set(2.0)
    clean = pacc(out, {"y": jnp.asarray([[[0, 0], [0, 0]]])})
    voided = pacc(out, {"y": jnp.asarray([[[0, 0], [255, -1]]])})
    assert float(clean) == pytest.approx(float(voided)) == pytest.approx(1.0)


def test_dice_and_smoothed_ce_ignore_void_labels():
    import jax.numpy as jnp
    from mlcomp_tpu.train.losses import create_loss

    dice = create_loss("dice")
    logits = jnp.zeros((1, 2, 2, 3)).at[..., 0].set(3.0)
    clean = dice(logits, {"y": jnp.asarray([[[0, 0], [0, 0]]])})
    # voiding half the pixels must not blow up the loss: excluded pixels
    # contribute to neither prediction nor target mass
    voided = dice(logits, {"y": jnp.asarray([[[0, 0], [255, -1]]])})
    assert float(voided) == pytest.approx(float(clean), abs=1e-4)

    sce = create_loss("smoothed_cross_entropy")
    lg = jnp.asarray([[3.0, 0.0, 0.0], [0.0, 3.0, 0.0], [3.0, 0.0, 0.0]])
    full = sce(lg[:2], {"y": jnp.asarray([0, 1])})
    with_void = sce(lg, {"y": jnp.asarray([0, 1, 255])})
    assert float(full) == pytest.approx(float(with_void), rel=1e-6)


def test_early_stopping_halts_on_plateau():
    cfg = mlp_cfg(epochs=20)
    cfg["optimizer"] = {"name": "sgd", "lr": 0.0}  # lr 0: instant plateau
    cfg["early_stop"] = {"metric": "valid/loss", "patience": 2}
    tr = Trainer(cfg)
    seen = []
    tr.fit(on_epoch=lambda e, s: seen.append(e))
    assert tr.stopped_early is not None
    # first epoch sets best; 2 more non-improving epochs trip patience=2
    assert len(seen) == 3, seen


def test_early_stopping_mode_validation():
    cfg = mlp_cfg()
    cfg["early_stop"] = {"mode": "sideways"}
    with pytest.raises(ValueError, match="early_stop.mode"):
        Trainer(cfg).fit()


def test_ema_tracked_and_used_for_eval():
    import jax.numpy as jnp

    cfg = mlp_cfg(epochs=1)
    cfg["ema"] = 0.9
    tr = Trainer(cfg)
    tr.train_epoch()
    assert tr.state.ema_params is not None
    # ema must lag the raw params after aggressive updates
    raw = jax.tree.leaves(tr.state.params)[0]
    ema = jax.tree.leaves(tr.state.ema_params)[0]
    assert not np.allclose(np.asarray(raw), np.asarray(ema))
    # eval_variables serves the ema copy
    assert np.allclose(
        np.asarray(jax.tree.leaves(tr.state.eval_variables["params"])[0]),
        np.asarray(ema),
    )
    # without ema config, eval_variables == variables
    tr2 = Trainer(mlp_cfg(epochs=1))
    assert tr2.state.ema_params is None
    assert tr2.state.eval_variables["params"] is tr2.state.params


def test_grad_accum_matches_full_batch():
    """N-microbatch accumulation must produce the same update as one big
    batch (mean losses, equal micro sizes, no batch-dependent layers)."""
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.loop import make_train_step
    from mlcomp_tpu.train.losses import create_loss
    from mlcomp_tpu.train.optim import create_optimizer
    from mlcomp_tpu.train.state import TrainState, init_model

    model = create_model({"name": "mlp", "num_classes": 4, "hidden": [16]})
    rs = np.random.RandomState(0)
    batch = {
        "x": jnp.asarray(rs.normal(size=(16, 8)), jnp.float32),
        "y": jnp.asarray(rs.randint(0, 4, size=(16,))),
    }
    loss_fn = create_loss("cross_entropy")

    def run(ga):
        params, model_state = init_model(
            model, {"x": batch["x"][:1]}, jax.random.PRNGKey(0)
        )
        tx = create_optimizer({"name": "sgd", "lr": 0.1})
        state = TrainState.create(model.apply, params, tx, model_state)
        step = jax.jit(make_train_step(loss_fn, {}, grad_accum=ga))
        state, stats = step(state, batch)
        return state, stats

    s1, st1 = run(1)
    s4, st4 = run(4)
    np.testing.assert_allclose(float(st1["loss"]), float(st4["loss"]), atol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_trainer_grad_accum_config():
    cfg = mlp_cfg()
    cfg["grad_accum"] = 2
    tr = Trainer(cfg)
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])
    assert int(tr.state.step) == tr.steps_per_epoch  # one update per batch


def test_grad_accum_rejects_indivisible_batch():
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.loop import make_train_step
    from mlcomp_tpu.train.losses import create_loss
    from mlcomp_tpu.train.optim import create_optimizer
    from mlcomp_tpu.train.state import TrainState, init_model

    model = create_model({"name": "mlp", "num_classes": 4, "hidden": [8]})
    batch = {
        "x": jnp.zeros((10, 8), jnp.float32),
        "y": jnp.zeros((10,), jnp.int32),
    }
    params, model_state = init_model(
        model, {"x": batch["x"][:1]}, jax.random.PRNGKey(0)
    )
    tx = create_optimizer({"name": "sgd", "lr": 0.1})
    state = TrainState.create(model.apply, params, tx, model_state)
    step = jax.jit(make_train_step(create_loss("cross_entropy"), {}, grad_accum=4))
    with pytest.raises(ValueError):
        step(state, batch)

"""Tier-1 wiring of tools/graftcheck.py: the JAX-aware static-analysis
suite.  Each pass is proven by a known-bad fixture (a seeded
use-after-donate, a tracer bool, an unlocked guarded write, an
undocumented env var must all FLAG), and the real package must come out
clean — zero unsuppressed findings — inside a 10 s wall budget."""

import os
import sys
import textwrap
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import graftcheck  # noqa: E402


def _mi(src: str, rel: str = "fixture.py") -> "graftcheck.ModuleInfo":
    return graftcheck.ModuleInfo(rel, rel, textwrap.dedent(src))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- donation


def test_use_after_donate_flags():
    mi = _mi(
        """
        import jax

        def train(state, batch):
            return state

        def run(state, batch):
            step = jax.jit(train, donate_argnums=(0,))
            out = step(state, batch)
            return state  # reads the donated buffer
        """
    )
    fs = graftcheck.check_donation(mi)
    assert any(f.rule == "use-after-donate" for f in fs), _rules(fs)


def test_rebind_idiom_is_clean():
    mi = _mi(
        """
        import jax

        def train(state, batch):
            return state

        def run(state, batches):
            step = jax.jit(train, donate_argnums=(0,))
            for b in batches:
                state = step(state, b)
            return state
        """
    )
    fs = [f for f in graftcheck.check_donation(mi)
          if f.rule == "use-after-donate"]
    assert not fs, [f.render() for f in fs]


def test_getter_idiom_use_after_donate():
    mi = _mi(
        """
        import jax

        class Engine:
            def _insert_fn(self):
                if "insert" not in self._fns:
                    def insert(dstate, row):
                        return dstate
                    self._fns["insert"] = jax.jit(
                        insert, donate_argnums=(0,)
                    )
                return self._fns["insert"]

            def bad(self, row):
                out = self._insert_fn()(self._dstate, row)
                return self._dstate  # donated above, never rebound

            def good(self, row):
                self._dstate = self._insert_fn()(self._dstate, row)
                return self._dstate
        """
    )
    fs = [f for f in graftcheck.check_donation(mi)
          if f.rule == "use-after-donate"]
    assert len(fs) == 1, [f.render() for f in fs]
    assert "self._dstate" in fs[0].message


def test_for_target_and_with_as_clear_taint():
    # rebinds through loop targets and `with ... as` are rebinds too
    mi = _mi(
        """
        import jax

        def train(state, batch):
            return state

        def run(state, batches, opener):
            step = jax.jit(train, donate_argnums=(0,))
            out = step(state, batches[0])
            for state in batches:
                pass
            with opener() as state:
                pass
            return state  # rebound twice since the donation
        """
    )
    fs = [f for f in graftcheck.check_donation(mi)
          if f.rule == "use-after-donate"]
    assert not fs, [f.render() for f in fs]


def test_donation_vector_consistency():
    mi = _mi(
        """
        import jax

        def dispatch(variables, dstate):
            return dstate

        fn = jax.jit(dispatch)  # carry not donated: must flag
        ok = jax.jit(dispatch, donate_argnums=(1,))
        """
    )
    fs = [f for f in graftcheck.check_donation(mi)
          if f.rule == "donation-vector"]
    assert len(fs) == 1, [f.render() for f in fs]


def test_donation_sharding_flags_reshard_of_donated_name():
    """The mesh-aware rule: resharding a donated carry name
    (device_put / with_sharding_constraint) in the same function that
    donates it flags — order-insensitive, because loop bodies donate
    and reuse across iterations."""
    mi = _mi(
        """
        import jax

        def dispatch(variables, dstate):
            return dstate

        class Eng:
            def _dispatch_fn(self):
                return jax.jit(dispatch, donate_argnums=(1,))

            def loop(self, sharding):
                while True:
                    self._dstate = jax.device_put(
                        self._dstate, sharding
                    )
                    self._dstate = self._dispatch_fn()(
                        self.variables, self._dstate
                    )

            def loop2(self, sharding):
                while True:
                    self._dstate = jax.lax.with_sharding_constraint(
                        self._dstate, sharding
                    )
                    self._dstate = self._dispatch_fn()(
                        self.variables, self._dstate
                    )
        """
    )
    fs = [f for f in graftcheck.check_donation(mi)
          if f.rule == "donation-sharding"]
    assert len(fs) == 2, [f.render() for f in fs]
    assert "device_put" in fs[0].message


def test_donation_sharding_clean_when_resharding_other_names():
    """In-trace constraints on NON-donated values (the engine's
    _constrain_carry on the traced output) and construction-time
    placement in a DIFFERENT function stay clean."""
    mi = _mi(
        """
        import jax

        def dispatch(variables, dstate):
            out = dict(dstate)
            out = jax.lax.with_sharding_constraint(out, None)
            return out

        class Eng:
            def _dispatch_fn(self):
                return jax.jit(dispatch, donate_argnums=(1,))

            def fresh(self, sharding):
                self._dstate = jax.device_put(self.init(), sharding)

            def loop(self):
                while True:
                    self._dstate = self._dispatch_fn()(
                        self.variables, self._dstate
                    )
        """
    )
    fs = [f for f in graftcheck.check_donation(mi)
          if f.rule == "donation-sharding"]
    assert not fs, [f.render() for f in fs]


# ---------------------------------------------------------------- trace


def test_trace_hazards_flag():
    mi = _mi(
        """
        import time
        import jax
        import jax.numpy as jnp
        import numpy as np

        def step(x):
            y = jnp.sum(x)
            if y > 0:            # tracer-control-flow
                pass
            t = time.time()      # traced-time
            z = float(y)         # host-sync
            w = np.asarray(y)    # host-sync
            v = y.item()         # host-sync
            return x

        f = jax.jit(step)
        """
    )
    fs = graftcheck.check_trace(mi)
    rules = [f.rule for f in fs]
    assert rules.count("tracer-control-flow") == 1, rules
    assert rules.count("traced-time") == 1, rules
    assert rules.count("host-sync") == 3, rules


def test_static_knob_params_are_not_tracers():
    # static Python config rides traced functions as plain params all
    # over the repo (top_k, causal, chunk widths) — must stay clean
    mi = _mi(
        """
        import jax
        import jax.numpy as jnp

        def step(x, top_k, causal):
            if top_k is not None:
                x = x + top_k
            if causal:
                x = x * 2
            n = x.shape[0]
            if n > 4:
                x = x[:4]
            return jnp.sum(x)

        f = jax.jit(step)
        """
    )
    fs = graftcheck.check_trace(mi)
    assert not fs, [f.render() for f in fs]


def test_scan_body_is_traced():
    mi = _mi(
        """
        import jax
        import jax.numpy as jnp

        def outer(xs):
            def body(carry, x):
                s = jnp.add(carry, x)
                if s > 0:  # flagged: scan bodies trace too
                    pass
                return s, s
            return jax.lax.scan(body, 0.0, xs)
        """
    )
    fs = graftcheck.check_trace(mi)
    assert any(f.rule == "tracer-control-flow" for f in fs), _rules(fs)


# ---------------------------------------------------------------- locks


LOCK_FIXTURE = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # guarded_by: _lock
            self._d = {}  # guarded_by: loop [writes]

        def bad_lock(self):
            self._n += 1

        def good_lock(self):
            with self._lock:
                self._n += 1

        def helper(self):  # graftcheck: holds(_lock)
            self._n += 1

        def loop_write(self):  # graftcheck: runs-on(loop)
            self._d["k"] = 1

        def bad_domain_write(self):
            self._d["k"] = 1

        def torn_read_ok(self):
            return dict(self._d)
"""


def test_lock_discipline_fixture():
    mods = {"fixture.py": _mi(LOCK_FIXTURE)}
    fs = graftcheck.check_locks(mods)
    by_line = {(f.line, f.rule) for f in fs}
    src = textwrap.dedent(LOCK_FIXTURE).splitlines()
    bad_lock_line = 1 + next(
        i for i, l in enumerate(src) if "def bad_lock" in l
    ) + 1
    bad_dom_line = 1 + next(
        i for i, l in enumerate(src) if "def bad_domain_write" in l
    ) + 1
    assert (bad_lock_line, "unguarded-write") in by_line, sorted(by_line)
    assert (bad_dom_line, "unguarded-write") in by_line, sorted(by_line)
    # exactly the two seeded violations: the locked/annotated/read
    # accesses all pass
    assert len(fs) == 2, [f.render() for f in fs]


def test_foreign_receiver_needs_matching_lock():
    mods = {"fixture.py": _mi(
        """
        import threading

        class Index:
            def __init__(self):
                self._lock = threading.Lock()
                self._pins = 0  # guarded_by: _lock

        class Lease:
            def ok(self, index):
                with index._lock:
                    index._pins -= 1

            def bad(self, index):
                index._pins -= 1
        """
    )}
    fs = graftcheck.check_locks(mods)
    assert len(fs) == 1 and fs[0].rule == "unguarded-write", (
        [f.render() for f in fs]
    )


def test_wrong_lock_is_not_accepted():
    # a same-named but DIFFERENT lock must not certify the access
    mods = {"fixture.py": _mi(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded_by: _lock

            def bad(self, _lock):
                with _lock:      # caller-supplied, not self._lock
                    self._n += 1
        """
    )}
    fs = graftcheck.check_locks(mods)
    assert len(fs) == 1 and fs[0].rule == "unguarded-write", (
        [f.render() for f in fs]
    )


def test_suppression_covers_multiline_statement():
    mi = _mi(
        """
        class C:
            def f(self):
                self._stats[
                    "k"
                ] += 1  # graftcheck: ignore[unguarded-write] -- reason
        """
    )
    # the finding anchors to the Attribute's line (the statement
    # start); the comment sits on the last physical line — both must
    # be covered
    assert "unguarded-write" in mi.suppress.get(4, set()), mi.suppress
    assert "unguarded-write" in mi.suppress.get(6, set()), mi.suppress


def test_suppression_parsing():
    mi = _mi(
        """
        x = 1  # graftcheck: ignore[unguarded-write] -- documented torn read
        y = 2  # graftcheck: ignore[metric-drift]
        """
    )
    assert mi.suppress.get(2) == {"unguarded-write"}
    assert mi.bad_suppressions == [3]  # no reason given


# ---------------------------------------------------------------- drift


def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))


def test_drift_fixture_project(tmp_path):
    root = str(tmp_path)
    _write(root, "mlcomp_tpu/mod.py", """
        import os
        from mlcomp_tpu.utils.faults import inject

        def f():
            inject("dead.point")
            return os.environ.get("MLCOMP_TPU_UNDOCUMENTED")
        """)
    _write(root, "mlcomp_tpu/engine.py", """
        def collect(m):
            m.counter("mlcomp_engine_real_total", "help")
            m.counter("mlcomp_engine_unlisted_total", "help")
        """)
    _write(root, "tools/obs_check.py", """
        DOCUMENTED_SERVE_METRICS = [
            "mlcomp_engine_real_total",
        ]
        """)
    _write(root, "docs/serving.md", """
        ## Environment variables

        | variable | read in | meaning |
        |---|---|---|
        | `MLCOMP_TPU_STALE_ROW` | nowhere | stale |
        """)
    _write(root, "docs/observability.md", """
        ## Metrics catalog — serve daemon

        | name | type | meaning |
        |---|---|---|
        | `mlcomp_engine_real_total` | counter | present in code |
        | `mlcomp_engine_stale_total` | counter | registered nowhere |
        """)
    _write(root, "README.md", "run with `--no-such-flag` for fun\n")
    fs = graftcheck.check_drift(root)
    msgs = "\n".join(f.render() for f in fs)
    # env: undocumented read + stale row
    assert "MLCOMP_TPU_UNDOCUMENTED" in msgs, msgs
    assert "MLCOMP_TPU_STALE_ROW" in msgs, msgs
    # metrics: registered-but-undocumented + documented-but-unregistered
    # + documented-but-unenforced (obs_check list)
    assert "mlcomp_engine_unlisted_total" in msgs, msgs
    assert "mlcomp_engine_stale_total" in msgs, msgs
    # fault point never armed anywhere
    assert "dead.point" in msgs, msgs
    # doc references a flag no add_argument defines
    assert "--no-such-flag" in msgs, msgs


def test_metric_docs_parser_handles_brace_expansion():
    docs = textwrap.dedent("""
        ## Metrics catalog — serve daemon

        | name | type | meaning |
        |---|---|---|
        | `mlcomp_prefix_cache_{hits,misses}_total` | counter | x |
        | `mlcomp_serving_requests_rejected_total{reason=…}` | counter | x |
        """)
    names = graftcheck.parse_metric_docs(docs)
    assert names == {
        "mlcomp_prefix_cache_hits_total",
        "mlcomp_prefix_cache_misses_total",
        "mlcomp_serving_requests_rejected_total",
    }, names


# ------------------------------------------------- the repo, end to end


def test_repo_is_clean_and_fast():
    """The acceptance gate: zero unsuppressed findings on the real
    repo, all four passes, inside the tier-1 wall budget."""
    t0 = time.monotonic()
    findings = graftcheck.run_passes(graftcheck.REPO)
    elapsed = time.monotonic() - t0
    assert not findings, "\n".join(f.render() for f in findings)
    assert elapsed < 10.0, f"graftcheck took {elapsed:.1f}s (budget 10s)"


def test_cli_entrypoint(tmp_path):
    # a tiny clean project keeps the CLI round trip off the full-repo
    # analysis (test_repo_is_clean_and_fast already pays that once)
    root = str(tmp_path)
    _write(root, "mlcomp_tpu/mod.py", "x = 1\n")
    _write(root, "docs/serving.md",
           "## Environment variables\n\n| variable |\n|---|\n")
    _write(root, "docs/observability.md",
           "## Metrics catalog — serve daemon\n\n| name |\n|---|\n")
    assert graftcheck.main(["--root", root]) == 0
    assert graftcheck.main(["--root", root, "--json"]) == 0
    assert graftcheck.main(
        ["--root", root, "--rules", "use-after-donate,host-sync"]
    ) == 0
    assert graftcheck.main(["--rules", "no-such-rule"]) == 2
    assert graftcheck.main(["--list-rules"]) == 0

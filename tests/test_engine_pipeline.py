"""Async double-buffered dispatch pipeline (engine pipeline_depth):
depth-2 output equality with the synchronous loop across cache
layouts, mixed knobs, mid-stream admission and EOS mid-dispatch;
close/submit races with a dispatch in flight; knob rejection; and the
overlap/latency metrics in stats()."""

import functools
import queue

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import DecodeEngine, _fail_future
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import GenerationService
from mlcomp_tpu.train.state import init_model


from conftest import (  # the shared compiled-program pool idiom
    close_pooled_engine as _close,
    share_engine_fns as _share,
)


@functools.lru_cache(maxsize=None)
def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


def _reference(model, params, ids, n_new, bucket=16, **kw):
    prompt = np.full((1, bucket), 0, np.int32)
    mask = np.zeros((1, bucket), bool)
    prompt[0, bucket - len(ids):] = ids
    mask[0, bucket - len(ids):] = True
    out = generate(
        model, {"params": params}, jnp.asarray(prompt), n_new,
        prompt_mask=jnp.asarray(mask), **kw,
    )
    return np.asarray(out)[0, bucket:].tolist()


def _mixed_workload(model, params, depth, kv_quant):
    """Drive one engine at the given depth through the satellite's
    workload: mixed knobs (greedy + logprobs, repetition penalty, an
    EOS that lands mid-dispatch), mixed lengths across two prompt
    buckets, and a mid-stream admission (C submitted while A streams,
    joining only when a slot frees).  Returns the comparable outputs
    (ids + logprobs; latencies excluded — the pipeline moves time)."""
    rs = np.random.RandomState(11)
    ids_a = rs.randint(1, 64, 5).tolist()
    ids_b = rs.randint(1, 64, 20).tolist()     # lands in the 32 bucket
    ids_c = rs.randint(1, 64, 3).tolist()
    # EOS mid-dispatch: C stops at its first greedy token, i.e. inside
    # step 1 of a K=2 dispatch (deterministic: greedy reference)
    eos_c = _reference(model, params, ids_c, 1, bucket=16)[0]
    eng = _share(
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16, 32), max_new_cap=12,
                     steps_per_dispatch=2, pipeline_depth=depth),
        ("mixed", kv_quant),
    )
    try:
        qa: "queue.Queue" = queue.Queue()
        fa = eng.submit(ids_a, 9, logprobs=True, stream=qa)
        qa.get(timeout=300)                    # A is decoding
        fb = eng.submit(ids_b, 7, repetition_penalty=1.5)
        fc = eng.submit(ids_c, 6, eos_id=eos_c)  # queues: slots full
        ra = fa.result(timeout=300)
        rb = fb.result(timeout=300)
        rc = fc.result(timeout=300)
        st = eng.stats()
        assert st["pipeline"]["depth"] == depth
        if depth > 1:
            # the pipeline actually ran overlapped at steady state
            assert st["pipeline"]["peak_inflight"] >= 2
    finally:
        _close(eng)
    return {
        "a": (ra["ids"], ra["logprobs"]),
        "b": rb["ids"],
        "c": rc["ids"],
        "eos_c": eos_c,
    }


@pytest.mark.parametrize("kv_quant", [False, True])
def test_depth2_bit_identical_to_depth1(kv_quant):
    """The acceptance equality: a depth-2 pipelined engine's outputs
    (tokens AND logprobs) are bit-identical to depth-1 for a
    mixed-knob, mixed-length workload on both cache layouts, including
    a mid-stream admission and an EOS mid-dispatch — the pipeline may
    reorder host work, never tokens."""
    model, params = _model_and_params(kv_quant)
    d1 = _mixed_workload(model, params, 1, kv_quant)
    d2 = _mixed_workload(model, params, 2, kv_quant)
    assert d1 == d2
    # and both match bare generate (not just each other)
    ids_a = d1["a"][0]
    rs = np.random.RandomState(11)
    ref_a = _reference(model, params, rs.randint(1, 64, 5).tolist(), 9)
    ref_b = _reference(
        model, params, rs.randint(1, 64, 20).tolist(), 7, bucket=32,
        temperature=jnp.zeros((1,)),
        repetition_penalty=jnp.asarray([1.5]),
    )
    assert ids_a == ref_a
    assert d1["b"] == ref_b
    assert d1["c"] == [d1["eos_c"]]            # EOS stopped it at one


def test_pipeline_join_bound_depth2():
    """A join under depth 2 pays at most the in-flight dispatch, one
    fused prefill+decode dispatch per run chunk (during which the
    decode fleet keeps advancing — the fused-admission contract), the
    insert drain, and its own first dispatch: first token within
    step_at_submit + 2 + n_chunks + (depth-1) steps at K=1 (one chunk
    here)."""
    model, params = _model_and_params()
    eng = _share(
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=16,
                     steps_per_dispatch=1, pipeline_depth=2),
        ("k1",),
    )
    try:
        qa: "queue.Queue" = queue.Queue()
        eng.submit([3, 14, 15, 9, 2], 16, stream=qa)
        qa.get(timeout=300)                    # A is decoding
        step_at_submit = eng.step_count
        qb: "queue.Queue" = queue.Queue()
        eng.submit([7, 3, 44], 2, stream=qb)
        first_b = qb.get(timeout=300)
        assert first_b["step"] <= step_at_submit + 4, (
            first_b, step_at_submit
        )
    finally:
        _close(eng)


def test_close_with_dispatch_in_flight_fails_pending_exactly_once():
    """The satellite race contract: close() with dispatches in flight
    resolves EVERY pending future exactly once (result or 'closed'
    error, never InvalidStateError), leaves nothing unread in the
    pipeline, and submit-after-close still raises cleanly."""
    model, params = _model_and_params()
    eng = _share(
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=16,
                     steps_per_dispatch=1, pipeline_depth=2),
        ("k1",),
    )
    q: "queue.Queue" = queue.Queue()
    futs = [eng.submit([3, 14, 15, 9, 2], 16, stream=q)]
    q.get(timeout=300)       # decoding: the pipeline holds a dispatch
    futs += [eng.submit([1, 2], 16) for _ in range(3)]  # active + queued
    if hasattr(eng, "_fns_pool"):
        eng._fns_pool.update(eng._fns)
    eng.close()
    assert not eng._thread.is_alive()
    assert not eng._inflight  # loop finally dropped the unread outputs
    for f in futs:
        assert f.done()
        try:
            f.result(timeout=0)
        except RuntimeError as e:
            assert "closed" in str(e)
    # exactly-once: a second failure attempt on an already-resolved
    # future is a no-op (the _fail_future idempotence contract)
    _fail_future(futs[0], RuntimeError("other"))
    if futs[0].exception() is not None:
        assert "closed" in str(futs[0].exception())
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit([1], 2)


def test_pipeline_depth_validation_and_mesh_default():
    """Depth < 1 is rejected at construction; the default is depth 2
    EVERYWHERE — mesh or not, since the sharded-serving PR (the old
    mesh rejection is gone; tests/test_engine_sharded.py pins the
    sharded equalities); depth > 1 at the service level needs the
    continuous batcher."""
    model, params = _model_and_params()
    kw = dict(slots=2, prompt_buckets=(16,), max_new_cap=8)
    with pytest.raises(ValueError, match="pipeline_depth"):
        DecodeEngine(model, {"params": params}, pipeline_depth=0, **kw)
    eng = DecodeEngine(model, {"params": params}, mesh=object(), **kw)
    try:
        assert eng.pipeline_depth == 2  # mesh default: pipelined too
    finally:
        eng.close()
    eng = DecodeEngine(model, {"params": params}, **kw)
    try:
        assert eng.pipeline_depth == 2  # single-chip default: pipelined
    finally:
        eng.close()
    with pytest.raises(ValueError, match="continuous"):
        GenerationService(
            model, {"params": params}, batcher="window", batch_sizes=(1,),
            prompt_buckets=(16,), max_new_buckets=(8,),
            engine_pipeline_depth=2,
        )


def test_pipeline_overlap_metrics_and_latency_percentiles():
    """stats() carries the overlap metrics (in-flight depth, hidden vs
    wait ms, occupancy) and per-request latency percentiles; the
    service surfaces both (latency at the top level for /healthz and
    the /api/serving proxy)."""
    model, params = _model_and_params()
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
    )
    try:
        svc.generate([5, 6, 7], 6)
        svc.generate([9, 2, 4], 6)
        st = svc.stats()
        pl = st["engine"]["pipeline"]
        assert pl["depth"] == 2
        assert pl["issued"] >= 2 and pl["peak_inflight"] == 2
        assert 1.0 <= pl["occupancy"] <= 2.0
        assert pl["host_hidden_ms_per_dispatch"] >= 0.0
        assert pl["resolve_wait_ms_per_dispatch"] >= 0.0
        assert 0.0 <= pl["overlap_efficiency"] <= 1.0
        lat = st["latency"]
        assert lat is st["engine"]["latency"]
        assert lat["samples"] == 2
        for key in ("ttft_ms", "per_token_ms"):
            pcts = lat[key]
            assert pcts["p50"] > 0
            assert pcts["p50"] <= pcts["p95"] <= pcts["p99"]
    finally:
        svc.close()


def test_report_server_serving_proxy_lifts_latency_and_pipeline():
    """/api/serving lifts the daemon's latency percentiles and
    pipeline overlap metrics to the top level of its payload."""
    import json
    import os
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from mlcomp_tpu.report.server import _Handler as ReportHandler

    health = {
        "ok": True,
        "latency": {"samples": 1,
                    "ttft_ms": {"p50": 5.0, "p95": 5.0, "p99": 5.0},
                    "per_token_ms": None},
        "engine": {"pipeline": {"depth": 2, "overlap_efficiency": 0.7}},
    }

    class Stub(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                body = json.dumps(health).encode()
                self.send_response(200)
            else:
                body = b'{"error": "disabled"}'
                self.send_response(404)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    stub = ThreadingHTTPServer(("127.0.0.1", 0), Stub)
    threading.Thread(target=stub.serve_forever, daemon=True).start()
    old = os.environ.get("MLCOMP_TPU_SERVE_URL")
    os.environ["MLCOMP_TPU_SERVE_URL"] = (
        f"http://127.0.0.1:{stub.server_address[1]}"
    )
    try:
        out = ReportHandler._r_serving(None, None)
        assert out["reachable"] is True
        assert out["latency"]["ttft_ms"]["p50"] == 5.0
        assert out["pipeline"]["depth"] == 2
        assert out["prefix_cache"] is None  # daemon runs without one
    finally:
        stub.shutdown()
        stub.server_close()
        if old is None:
            os.environ.pop("MLCOMP_TPU_SERVE_URL", None)
        else:
            os.environ["MLCOMP_TPU_SERVE_URL"] = old

"""image_folder dataset, loader metadata, best-checkpoint tracking."""

import numpy as np
import pytest

from mlcomp_tpu.data.datasets import create_dataset
from mlcomp_tpu.data.loader import DataLoader


@pytest.fixture()
def image_tree(tmp_path):
    from PIL import Image

    for cls, color in [("cat", (255, 0, 0)), ("dog", (0, 255, 0))]:
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            Image.new("RGB", (10, 8), color).save(d / f"{i}.png")
    return str(tmp_path)


def test_image_folder_loads_tree(image_tree):
    ds = create_dataset({"name": "image_folder", "path": image_tree, "image": 16})
    assert ds["x"].shape == (6, 16, 16, 3)
    assert ds["x"].dtype == np.float32 and ds["x"].max() <= 1.0
    assert ds["y"].tolist() == [0, 0, 0, 1, 1, 1]
    assert ds["_class_names"] == ["cat", "dog"]
    # red channel dominates for 'cat' images
    assert ds["x"][0, 0, 0, 0] == pytest.approx(1.0)


def test_image_folder_limit(image_tree):
    ds = create_dataset(
        {"name": "image_folder", "path": image_tree, "image": 8, "limit": 1}
    )
    assert len(ds["y"]) == 2


def test_loader_keeps_meta_out_of_batches(image_tree):
    ds = create_dataset({"name": "image_folder", "path": image_tree, "image": 8})
    dl = DataLoader(ds, batch_size=3, shuffle=False, mesh=None)
    assert dl.meta["_class_names"] == ["cat", "dog"]
    batch = next(iter(dl))
    assert set(batch) == {"x", "y"}


def test_valid_report_uses_dataset_class_names(tmp_db, image_tree):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="v", executor="valid"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "model": {"name": "mlp", "hidden": [8], "num_classes": 2},
        "loss": "cross_entropy",
        "metrics": [],
        "data": {
            "valid": {"name": "image_folder", "path": image_tree,
                      "image": 8, "batch_size": 8}
        },
        "report": {"kind": "classification"},
    }
    ok, _, err = run_task(
        "valid",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="v",
                         args=cfg, store=store),
    )
    assert ok, err
    payload = store.report_payload(store.reports(tid)[0]["id"])
    assert payload["class_names"] == ["cat", "dog"]
    store.close()


def test_best_checkpoint_tracking(tmp_db, tmp_path):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task
    from mlcomp_tpu.io.checkpoint import latest_step

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="train"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "storage_root": str(tmp_path),
        "model": {"name": "mlp", "hidden": [16], "num_classes": 3},
        "optimizer": {"name": "adam", "lr": 5e-2},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "epochs": 3,
        "best_metric": "valid/accuracy",
        "data": {
            "train": {"name": "synthetic_classification", "n": 64,
                      "num_classes": 3, "dim": 8, "batch_size": 16},
            "valid": {"name": "synthetic_classification", "n": 32,
                      "num_classes": 3, "dim": 8, "seed": 1, "batch_size": 16},
        },
    }
    ok, result, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=cfg, store=store),
    )
    assert ok, err
    assert "best" in result and result["best"]["metric"] == "valid/accuracy"
    assert result["best"]["value"] is not None
    assert latest_step(result["best"]["ckpt_dir"]) == result["best"]["step"]
    store.close()


def test_best_mode_validation():
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    ok, _, err = run_task(
        "train",
        ExecutionContext(dag_id=1, task_id=1, task_name="t",
                         args={"best_mode": "upwards"}, store=None),
    )
    assert not ok and "best_mode" in err


def test_best_survives_resume(tmp_db, tmp_path):
    """Restarted training must not overwrite a better pre-restart best."""
    from mlcomp_tpu.io.storage import ModelStorage

    storage = ModelStorage(str(tmp_path))
    # simulate a pre-restart run that recorded best accuracy 0.99
    storage.write_meta(
        "default", "dag1", "t",
        {"best": {"metric": "valid/accuracy", "value": 0.99, "epoch": 1,
                  "step": 4}},
    )
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="train"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "storage_root": str(tmp_path),
        "dag_name": "dag1",
        "model": {"name": "mlp", "hidden": [4], "num_classes": 3},
        "optimizer": {"name": "sgd", "lr": 1e-4},  # barely learns
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "epochs": 1,
        "best_metric": "valid/accuracy",
        "data": {
            "train": {"name": "synthetic_classification", "n": 32,
                      "num_classes": 3, "dim": 8, "batch_size": 16},
            "valid": {"name": "synthetic_classification", "n": 16,
                      "num_classes": 3, "dim": 8, "seed": 1, "batch_size": 16},
        },
    }
    ok, result, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=cfg, store=store),
    )
    assert ok, err
    # one low-lr epoch can't beat 0.99: prior best must be preserved
    assert result["best"]["value"] == 0.99 and result["best"]["epoch"] == 1
    store.close()


def test_missing_best_metric_warns(tmp_db, tmp_path):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="train"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    cfg = {
        "storage_root": str(tmp_path),
        "model": {"name": "mlp", "hidden": [4], "num_classes": 3},
        "optimizer": {"name": "sgd", "lr": 1e-3},
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": 2,
        "best_metric": "accuracy",  # unprefixed: never in stats
        "data": {
            "train": {"name": "synthetic_classification", "n": 32,
                      "num_classes": 3, "dim": 8, "batch_size": 16},
        },
    }
    ok, result, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=cfg, store=store),
    )
    assert ok, err
    msgs = [l["message"] for l in store.task_logs(tid)]
    warnings = [m for m in msgs if "best_metric" in m and "not in epoch stats" in m]
    assert len(warnings) == 1, msgs  # warned once, not per epoch
    assert "best" not in result
    store.close()


def _es_cfg(tmp_path, epochs=15):
    return {
        "storage_root": str(tmp_path),
        "dag_name": "dag1",
        "model": {"name": "mlp", "hidden": [8], "num_classes": 3},
        "optimizer": {"name": "sgd", "lr": 0.0},  # instant plateau
        "loss": "cross_entropy",
        "metrics": [],
        "epochs": epochs,
        "early_stop": {"metric": "valid/loss", "patience": 2},
        "data": {
            "train": {"name": "synthetic_classification", "n": 32,
                      "num_classes": 3, "dim": 8, "batch_size": 16},
            "valid": {"name": "synthetic_classification", "n": 16,
                      "num_classes": 3, "dim": 8, "seed": 1, "batch_size": 16},
        },
    }


def test_early_stop_decision_survives_restart(tmp_db, tmp_path):
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="train"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    ok, r1, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=_es_cfg(tmp_path), store=store),
    )
    assert ok, err
    assert r1["early_stopped"] == 2  # epoch 0 best + 2 plateau epochs
    steps_after_first = 3 * 2  # 3 epochs x 2 steps/epoch

    # re-run the same task (restart): the verdict must stand, no new epochs
    ok, r2, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=_es_cfg(tmp_path), store=store),
    )
    assert ok, err
    msgs = [l["message"] for l in store.task_logs(tid)]
    assert any("early stop from prior run stands" in m for m in msgs), msgs

    # raising the epoch budget re-enables training
    ok, r3, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=_es_cfg(tmp_path, epochs=30), store=store),
    )
    assert ok, err
    store.close()


def test_ema_checkpoint_cross_restore(tmp_path):
    """EMA/non-EMA checkpoint-target mismatches restore adaptively."""
    import jax
    import jax.numpy as jnp
    from mlcomp_tpu.io.checkpoint import restore_checkpoint, save_checkpoint
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.optim import create_optimizer
    from mlcomp_tpu.train.state import TrainState, init_model

    m = create_model({"name": "mlp", "hidden": [8], "num_classes": 3})
    p, ms = init_model(m, {"x": jnp.zeros((1, 4))}, jax.random.PRNGKey(0))
    tx = create_optimizer({"name": "sgd", "lr": 0.1})

    # saved WITH ema -> restored into a non-ema target: the EMA weights
    # BECOME the params (nothing would keep a dangling EMA copy updated)
    with_ema = TrainState.create(m.apply, p, tx, ms, ema_decay=0.9)
    # make ema distinguishable from raw params
    with_ema = with_ema.replace(
        ema_params=jax.tree.map(lambda x: x + 1.0, with_ema.params)
    )
    save_checkpoint(str(tmp_path / "a"), with_ema, step=1)
    plain_target = TrainState.create(m.apply, p, tx, ms)
    restored = restore_checkpoint(str(tmp_path / "a"), plain_target)
    assert restored.ema_params is None
    got = jax.tree.leaves(restored.params)[0]
    want = jax.tree.leaves(with_ema.ema_params)[0]
    assert np.allclose(np.asarray(got), np.asarray(want))
    # eval on this state now runs on the (restored) EMA weights
    assert restored.eval_variables["params"] is restored.params

    # saved WITHOUT ema -> restored into an ema target: seeded from params
    plain = TrainState.create(m.apply, p, tx, ms)
    save_checkpoint(str(tmp_path / "b"), plain, step=1)
    ema_target = TrainState.create(m.apply, p, tx, ms, ema_decay=0.9)
    restored = restore_checkpoint(str(tmp_path / "b"), ema_target)
    assert restored.ema_params is not None
    a = jax.tree.leaves(restored.ema_params)[0]
    b = jax.tree.leaves(restored.params)[0]
    assert np.allclose(np.asarray(a), np.asarray(b))


def test_relaxed_early_stop_config_reenables_training(tmp_db, tmp_path):
    """Changing the early_stop criteria invalidates the prior verdict."""
    from mlcomp_tpu.dag.schema import DagSpec, TaskSpec
    from mlcomp_tpu.db.store import Store
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, run_task

    load_all()
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="t", executor="train"),))
    )
    tid = store.task_rows(dag_id)[0]["id"]
    ok, r1, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=_es_cfg(tmp_path), store=store),
    )
    assert ok and r1["early_stopped"] == 2, err
    assert r1["final"], "final metrics recorded"

    # restart with same config: verdict stands AND prior final preserved
    ok, r2, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=_es_cfg(tmp_path), store=store),
    )
    assert ok, err
    assert r2["early_stopped"] == 2
    assert r2["final"] == r1["final"], "skip must not clobber final metrics"

    # raise patience: training re-enabled (plateau re-trips later)
    cfg = _es_cfg(tmp_path)
    cfg["early_stop"] = {"metric": "valid/loss", "patience": 5}
    ok, r3, err = run_task(
        "train",
        ExecutionContext(dag_id=dag_id, task_id=tid, task_name="t",
                         args=cfg, store=store),
    )
    assert ok, err
    msgs = [l["message"] for l in store.task_logs(tid)]
    # third run must NOT log the verdict-stands skip for the new config
    stands = [m for m in msgs if "stands" in m]
    assert len(stands) == 1, msgs  # only the second run skipped
    store.close()

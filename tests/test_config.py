import os

import pytest

from mlcomp_tpu.utils.config import (
    ConfigError,
    interpolate,
    load_config,
    loads_config,
    merge_config,
)


def test_merge_deep():
    base = {"a": {"b": 1, "c": 2}, "d": [1, 2]}
    out = merge_config(base, {"a": {"c": 3}, "d": [9]})
    assert out == {"a": {"b": 1, "c": 3}, "d": [9]}
    assert base["a"]["c"] == 2  # no mutation


def test_interpolate_reference_keeps_type():
    cfg = interpolate({"lr": 0.001, "opt": {"lr": "${lr}"}})
    assert cfg["opt"]["lr"] == 0.001
    assert isinstance(cfg["opt"]["lr"], float)


def test_interpolate_string_embedding():
    cfg = interpolate({"name": "exp", "path": "/tmp/${name}/run"})
    assert cfg["path"] == "/tmp/exp/run"


def test_interpolate_env(monkeypatch):
    monkeypatch.setenv("MLC_TEST_VAR", "hello")
    cfg = interpolate({"a": "${env:MLC_TEST_VAR}", "b": "${env:MISSING_X,fallback}"})
    assert cfg == {"a": "hello", "b": "fallback"}


def test_interpolate_missing_raises():
    with pytest.raises(ConfigError):
        interpolate({"a": "${nope.nope}"})


def test_load_with_base(tmp_path):
    (tmp_path / "base.yml").write_text("a: 1\nb: {c: 2}\n")
    (tmp_path / "child.yml").write_text("_base_: base.yml\nb: {c: 3}\n")
    cfg = load_config(tmp_path / "child.yml")
    assert cfg == {"a": 1, "b": {"c": 3}}


def test_loads_and_overrides(tmp_path):
    p = tmp_path / "x.yml"
    p.write_text("a: 1\nb: 2\n")
    cfg = load_config(p, overrides={"b": 7})
    assert cfg == {"a": 1, "b": 7}
    assert loads_config("x: [1, 2]") == {"x": [1, 2]}

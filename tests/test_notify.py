"""Notification sinks + supervisor lifecycle events."""

import json

from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.supervisor import Supervisor
from mlcomp_tpu.utils.notify import (
    FileNotifier,
    create_notifiers,
    notify_all,
)


def _events(path):
    with open(path) as f:
        return [json.loads(l) for l in f]


def test_file_notifier_appends_jsonl(tmp_path):
    p = str(tmp_path / "events.jsonl")
    n = FileNotifier(p)
    n.send({"event": "a"})
    n.send({"event": "b"})
    assert [e["event"] for e in _events(p)] == ["a", "b"]


def test_command_notifier_pipes_json(tmp_path):
    out = tmp_path / "cmd.json"
    ns = create_notifiers([{"type": "command", "cmd": f"cat > {out}"}])
    notify_all(ns, "task_failed", task="t1")
    got = json.loads(out.read_text())
    assert got["event"] == "task_failed" and got["task"] == "t1"


def test_notify_all_survives_failing_sink(tmp_path):
    p = str(tmp_path / "ok.jsonl")
    errors = []
    ns = create_notifiers(
        [
            {"type": "command", "cmd": "exit 3"},  # always fails
            {"type": "file", "path": p},
        ]
    )
    notify_all(ns, "dag_finished", dag_id=1, on_error=errors.append)
    assert len(_events(p)) == 1  # healthy sink still fired
    assert len(errors) == 1 and "failed" in errors[0]


def test_supervisor_notifies_dag_finished_once(tmp_db, tmp_path):
    p = str(tmp_path / "events.jsonl")
    store = Store(tmp_db)
    dag_id = store.submit_dag(
        DagSpec(name="d", project="p", tasks=(TaskSpec(name="a", executor="noop"),))
    )
    sup = Supervisor(store, notifiers=[{"type": "file", "path": p}])
    sup.tick()  # queues the task; dag still in progress
    store.set_task_status(dag_id, ["a"], TaskStatus.SUCCESS)
    sup.tick()  # finalizes + notifies
    sup.tick()  # must not notify again (status already terminal)
    evs = [e for e in _events(p) if e["event"] == "dag_finished"]
    assert len(evs) == 1
    assert evs[0]["status"] == "success" and evs[0]["tasks"] == {"a": "success"}
    store.close()

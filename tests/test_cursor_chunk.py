"""Per-row-cursor multi-token decode (``cache_cursor`` with s > 1):
the engine's speculative-verify contract in models/transformer.py.

A chunked forward at per-row cursors must produce, position by
position, the same logits as feeding the same tokens one step at a
time through the s == 1 cursor path — for both cache modes (bf16 and
int8 KV; the latter routes the multi-query flash kernel)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import init_cache


def _setup(kv_quant, heads=2, kv_heads=None):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": heads, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
        **({"kv_heads": kv_heads} if kv_heads else {}),
    })
    rs = np.random.RandomState(3)
    prompts = jnp.asarray(rs.randint(1, 64, (2, 6)))
    params, _ = init_model_params(model, prompts)
    return model, params, prompts


def init_model_params(model, prompts):
    from mlcomp_tpu.train.state import init_model

    return init_model(model, {"x": prompts}, jax.random.PRNGKey(0))


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("kv_heads", [None, 1])
def test_cursor_chunk_matches_stepwise(kv_quant, kv_heads):
    model, params, prompts = _setup(kv_quant, kv_heads=kv_heads)
    b, s0 = prompts.shape
    l_buf = 32
    s_chunk = 3

    def prefill(cache):
        pos = jnp.broadcast_to(jnp.arange(s0, dtype=jnp.int32)[None], (b, s0))
        logits, upd = model.apply(
            {"params": params, "cache": cache}, prompts, decode=True,
            positions=pos, mutable=["cache"],
        )
        return logits, upd["cache"]

    # rows sit at DIFFERENT depths: advance row 1 by two extra steps
    # through the s=1 cursor path so cursors diverge
    rs = np.random.RandomState(9)
    extra = jnp.asarray(rs.randint(1, 64, (b, 1)))
    chunk_toks = jnp.asarray(rs.randint(1, 64, (b, s_chunk)))

    def advance_row1(cache, cursors, positions):
        # row 0's write lands at its own cursor too, but we only CARE
        # about row 1; keep both rows' tokens identical so row 0's
        # state stays deterministic across both pipelines
        for _ in range(2):
            _, upd = model.apply(
                {"params": params, "cache": cache}, extra, decode=True,
                positions=positions[:, None], cache_cursor=cursors,
                mutable=["cache"],
            )
            cache = upd["cache"]
            cursors = cursors + 1
            positions = positions + 1
        return cache, cursors, positions

    _, cache0 = prefill(init_cache(model, b, l_buf))
    cursors = jnp.full((b,), s0, jnp.int32)
    positions = jnp.full((b,), s0, jnp.int32)
    cache0, cursors, positions = advance_row1(cache0, cursors, positions)

    # pipeline A: one s=3 chunked forward at per-row cursors
    pos_chunk = positions[:, None] + jnp.arange(s_chunk, dtype=jnp.int32)
    logits_chunk, updA = model.apply(
        {"params": params, "cache": cache0}, chunk_toks, decode=True,
        positions=pos_chunk, cache_cursor=cursors, mutable=["cache"],
    )

    # pipeline B: the same tokens one s=1 step at a time
    cacheB, curB, posB = cache0, cursors, positions
    step_logits = []
    for j in range(s_chunk):
        lg, upd = model.apply(
            {"params": params, "cache": cacheB}, chunk_toks[:, j:j + 1],
            decode=True, positions=posB[:, None], cache_cursor=curB,
            mutable=["cache"],
        )
        step_logits.append(lg[:, 0])
        cacheB, curB, posB = upd["cache"], curB + 1, posB + 1
    ref = jnp.stack(step_logits, axis=1)

    np.testing.assert_allclose(
        np.asarray(logits_chunk), np.asarray(ref),
        atol=3e-2 if kv_quant else 1e-4, rtol=1e-3,
    )
    # the caches agree afterwards too (same slots written)
    for a_leaf, b_leaf in zip(
        jax.tree.leaves(updA["cache"]), jax.tree.leaves(cacheB)
    ):
        if a_leaf.ndim == 0:
            continue  # cache_index: unused under cursors
        np.testing.assert_allclose(
            np.asarray(a_leaf, np.float32), np.asarray(b_leaf, np.float32),
            atol=1e-5,
        )

"""Chunked fused linear+CE vs the materialized-logits reference."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlcomp_tpu.ops.fused_ce import fused_linear_cross_entropy


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).normal(size=shape), dtype)


@pytest.mark.parametrize("chunk", [16, 32, 64])
def test_fused_matches_reference(chunk):
    b, s, d, v = 2, 64, 32, 96
    h = _rand((b, s, d), 0)
    w = _rand((d, v), 1) * 0.1
    y = jnp.asarray(np.random.RandomState(2).randint(0, v, (b, s)))
    gw = _rand((b, s), 3)

    def ref(h, w):
        return optax.softmax_cross_entropy_with_integer_labels(h @ w, y)

    out = fused_linear_cross_entropy(h, w, y, chunk)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref(h, w)), atol=1e-5
    )
    gf = jax.grad(
        lambda h, w: jnp.sum(fused_linear_cross_entropy(h, w, y, chunk) * gw),
        argnums=(0, 1),
    )(h, w)
    gr = jax.grad(
        lambda h, w: jnp.sum(ref(h, w) * gw), argnums=(0, 1)
    )(h, w)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)


def test_fused_rejects_indivisible_chunk():
    with pytest.raises(ValueError, match="divisible"):
        fused_linear_cross_entropy(
            jnp.zeros((1, 10, 4)), jnp.zeros((4, 8)),
            jnp.zeros((1, 10), jnp.int32), 3,
        )


def test_model_fused_loss_matches_plain():
    """fused_loss model trains to the same loss value as the plain model
    with identical params, and its gradients match."""
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.losses import create_loss
    from mlcomp_tpu.train.state import init_model

    cfg = {"name": "transformer_lm", "vocab_size": 64, "hidden": 32,
           "layers": 2, "heads": 4, "dtype": "float32"}
    plain = create_model(cfg)
    fused = create_model({**cfg, "fused_loss": True, "fused_loss_chunk": 8})
    x = jnp.asarray(np.random.RandomState(5).randint(1, 64, (2, 16)))
    params, _ = init_model(plain, {"x": x}, jax.random.PRNGKey(0))
    batch = {"x": x}
    plain_loss = create_loss("lm_cross_entropy")
    fused_loss = create_loss("lm_cross_entropy_fused")

    def lp(p):
        return plain_loss(plain.apply({"params": p}, x), batch)

    def lf(p):
        return fused_loss(fused.apply({"params": p}, x), batch)

    np.testing.assert_allclose(float(lp(params)), float(lf(params)), rtol=1e-6)
    gp = jax.grad(lp)(params)
    gf = jax.grad(lf)(params)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fused_model_still_generates():
    """decode path is untouched by fused_loss (logits as usual)."""
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import generate
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 32, "hidden": 16,
        "layers": 1, "heads": 2, "dtype": "float32", "fused_loss": True,
    })
    prompt = jnp.asarray(np.random.RandomState(6).randint(1, 32, (2, 4)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    out = generate(model, {"params": params}, prompt, 3)
    assert out.shape == (2, 7)


def test_fused_loss_rejects_logits():
    from mlcomp_tpu.train.losses import create_loss

    with pytest.raises(ValueError, match="per-token"):
        create_loss("lm_cross_entropy_fused")(jnp.zeros((2, 8, 32)), {})


def test_lm_feature_matrix_composes():
    """The LM memory/parallelism knobs compose: fused_loss + remat +
    sequence parallelism + grad_accum + adafactor in one jitted step."""
    import numpy as np

    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "transformer_lm", "vocab_size": 64, "hidden": 32,
                  "layers": 2, "heads": 4, "dtype": "float32",
                  "fused_loss": True, "fused_loss_chunk": 32, "remat": True,
                  "seq_parallel": "ring"},
        "optimizer": {"name": "adafactor", "lr": 1e-3},
        "loss": "lm_cross_entropy_fused", "metrics": [], "epochs": 1,
        "seed": 0, "grad_accum": 2,
        "mesh": {"dp": 2, "sp": 4},
        "data": {"train": {"name": "synthetic_tokens", "n": 8,
                           "seq_len": 64, "vocab_size": 64,
                           "batch_size": 4}},
    }
    stats = Trainer(cfg).train_epoch()
    assert np.isfinite(stats["loss"])

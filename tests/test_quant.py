"""Int8 weight-only quantization: roundtrip bounds, generation fidelity."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.ops.quant import (
    dequantize_params,
    has_quantized,
    is_quantized_leaf,
    quantize_params,
)


def test_roundtrip_error_bounded():
    w = jnp.asarray(np.random.RandomState(0).normal(size=(128, 64)), jnp.float32)
    q = quantize_params({"w": w}, min_size=1)
    back = dequantize_params(q, jnp.float32)["w"]
    # absmax int8: error <= scale/2 = absmax/254 per channel
    err = np.abs(np.asarray(back) - np.asarray(w))
    bound = np.abs(np.asarray(w)).max(axis=0, keepdims=True) / 254 + 1e-7
    assert (err <= bound).all()


def test_small_and_1d_leaves_pass_through():
    params = {
        "bias": jnp.ones((64,)),
        "norm": jnp.ones((8, 8)),          # below min_size
        "big": jnp.ones((256, 64)),
    }
    q = quantize_params(params)
    assert not is_quantized_leaf(q["bias"]) and q["bias"].dtype == jnp.float32
    assert not is_quantized_leaf(q["norm"])
    assert is_quantized_leaf(q["big"]) and q["big"]["q8"].dtype == jnp.int8
    assert has_quantized(q) and not has_quantized(params)


def test_quantized_generation_close_to_full_precision():
    model = create_model(
        {
            "name": "transformer_lm",
            "vocab_size": 64,
            "hidden": 64,
            "layers": 2,
            "heads": 4,
            "dtype": "float32",
        }
    )
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(1, 64, size=(4, 8)), jnp.int32
    )
    variables = {"params": model.init(jax.random.PRNGKey(0), prompt)["params"]}
    qvars = {"params": quantize_params(variables["params"], min_size=1024)}

    gen = jax.jit(
        partial(generate, model, max_new_tokens=8, weights_dtype=jnp.float32)
    )
    full = np.asarray(gen(variables, prompt=prompt))
    quant = np.asarray(gen(qvars, prompt=prompt))
    assert full.shape == quant.shape == (4, 16)
    # random (untrained) weights make near-ties common; quantization may
    # flip some argmaxes, but the sequences must stay predominantly equal
    agree = (full[:, 8:] == quant[:, 8:]).mean()
    assert agree >= 0.5, f"only {agree:.0%} of tokens agree"
    # and the model's logits under quantized weights stay close
    lf = model.apply(variables, prompt)
    lq = model.apply(
        {"params": dequantize_params(qvars["params"], jnp.float32)}, prompt
    )
    np.testing.assert_allclose(
        np.asarray(lq), np.asarray(lf), atol=0.15, rtol=0.1
    )


def test_quant_matmul_matches_dequant():
    """Pallas int8 matmul == x @ dequantized(W) within int8 tolerance."""
    import jax.numpy as jnp

    from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
    from mlcomp_tpu.ops.quant import dequantize_leaf, quantize_leaf

    rs = np.random.RandomState(0)
    for b, d, n in [(1, 256, 512), (4, 512, 1024), (9, 256, 256)]:
        w = jnp.asarray(rs.normal(size=(d, n)), jnp.float32) * 0.05
        x = jnp.asarray(rs.normal(size=(b, d)), jnp.bfloat16)
        ql = quantize_leaf(w)
        ref = x.astype(jnp.float32) @ dequantize_leaf(ql, jnp.float32)
        out = quant_matmul(x, ql["q8"], ql["q8_scale"].reshape(-1))
        rel = float(
            jnp.max(jnp.abs(out.astype(jnp.float32) - ref))
        ) / float(jnp.max(jnp.abs(ref)))
        assert rel < 0.02, (b, d, n, rel)


def test_quant_kernel_interception_dense_embed():
    """Under interception, Dense/Embed consume int8 leaves directly and
    match the dequantized computation."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.ops.quant import (
        dequantize_params,
        quant_kernel_interception,
        quantize_params,
    )

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, ids):
            h = nn.Embed(64, 256, dtype=jnp.bfloat16, name="emb")(ids)
            h = nn.Dense(512, use_bias=False, dtype=jnp.bfloat16)(h)
            return nn.Dense(64, use_bias=True, dtype=jnp.float32)(h)

    m = Tiny()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    qp = quantize_params(params, min_size=1024)
    ref = m.apply({"params": dequantize_params(qp, jnp.bfloat16)}, ids)
    with quant_kernel_interception():
        out = m.apply({"params": qp}, ids)
    assert out.dtype == ref.dtype
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=0.1, rtol=0.1,
    )


def test_generate_quant_kernel_runs():
    """generate(quant_kernel=True) produces the right shapes on the
    interpret path (CPU) and matches entry-dequant closely enough that
    the first greedy tokens agree."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import generate
    from mlcomp_tpu.ops.quant import quantize_params
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 128,
        "layers": 1, "heads": 2, "mlp_dim": 256, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(3).randint(1, 128, (2, 4)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    a = generate(model, q, prompt, 3)
    b = generate(model, q, prompt, 3, quant_kernel=True)
    assert a.shape == b.shape == (2, 7)
    # same int8 source: the very first sampled token must agree
    np.testing.assert_array_equal(np.asarray(a[:, 4]), np.asarray(b[:, 4]))


def test_attention_projections_stay_int8_and_match():
    """Round 3: the 3-D q/k/v/out DenseGeneral kernels are quantized
    along their true contraction axes, survive dequantize_nonkernel_params
    as int8, and compute through interception to the same result as
    entry dequantization."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.ops.quant import (
        dequantize_nonkernel_params,
        dequantize_params,
        is_quantized_leaf,
        quant_kernel_interception,
        quantize_params,
    )

    # hidden=256, heads=2 -> d_head=128: q/k/v fold (256, 256), out folds
    # (256, 256) — lane-tileable, so the Pallas path is exercised (the
    # interpret path on CPU)
    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 1, "heads": 2, "mlp_dim": 512, "dtype": "float32",
    })
    ids = jnp.asarray(np.random.RandomState(1).randint(1, 128, (2, 8)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    qp = quantize_params(params, min_size=1024)

    attn = qp["DecoderLayer_0"]["attn"]
    for name, want_scale in [
        ("q", (1, 2, 128)), ("k", (1, 2, 128)), ("v", (1, 2, 128)),
        ("out", (1, 1, 256)),
    ]:
        leaf = attn[name]["kernel"]
        assert is_quantized_leaf(leaf), name
        assert leaf["q8_scale"].shape == want_scale, (name, leaf["q8_scale"].shape)

    kept = dequantize_nonkernel_params(qp, jnp.float32)
    for name in ("q", "k", "v", "out"):
        assert is_quantized_leaf(kept["DecoderLayer_0"]["attn"][name]["kernel"]), (
            f"{name} projection was dequantized at entry — should stay int8"
        )

    ref = model.apply({"params": dequantize_params(qp, jnp.float32)}, ids)
    with quant_kernel_interception():
        out = model.apply({"params": kept}, ids)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=0.05, rtol=0.05,
    )


def test_dense_general_3d_interception_with_bias():
    """BERT-style DenseGeneral projections (use_bias=True) through the
    interceptor: q-style (axis=-1, 3-D kernel) and out-style
    (axis=(-2,-1)) both match the dequantized computation."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.ops.quant import (
        dequantize_params,
        quant_kernel_interception,
        quantize_params,
    )

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.DenseGeneral((2, 128), dtype=jnp.float32, name="q")(x)
            return nn.DenseGeneral(
                256, axis=(-2, -1), dtype=jnp.float32, name="out"
            )(h)

    m = Block()
    x = jnp.asarray(np.random.RandomState(2).normal(size=(4, 256)), jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    qp = quantize_params(params, min_size=1024)
    assert qp["q"]["kernel"]["q8_scale"].shape == (1, 2, 128)
    assert qp["out"]["kernel"]["q8_scale"].shape == (1, 1, 256)
    ref = m.apply({"params": dequantize_params(qp, jnp.float32)}, x)
    with quant_kernel_interception():
        out = m.apply({"params": qp}, x)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=0.05, rtol=0.05,
    )


def test_mqa_single_kv_head_quant_decode():
    """kv_heads=1 (MQA): the k/v kernels are (d, 1, dh) — the folded
    shape is the same matrix under either axis grouping; generation with
    quant_kernel=True stays consistent with entry dequant."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import generate
    from mlcomp_tpu.ops.quant import quantize_params
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 1, "heads": 2, "kv_heads": 1, "mlp_dim": 512,
        "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(5).randint(1, 128, (2, 4)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    a = generate(model, q, prompt, 3)
    b = generate(model, q, prompt, 3, quant_kernel=True)
    assert a.shape == b.shape == (2, 7)
    np.testing.assert_array_equal(np.asarray(a[:, 4]), np.asarray(b[:, 4]))


def test_quant_matmul_rejects_non_channel_scale():
    """ADVICE r2: a per-input-row (d, 1) scale on a square kernel must be
    rejected, not silently misused."""
    import pytest as _pytest

    from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.normal(size=(2, 256)), jnp.bfloat16)
    q8 = jnp.asarray(rs.randint(-127, 127, size=(256, 256)), jnp.int8)
    bad = jnp.ones((256, 1), jnp.float32)
    with _pytest.raises(ValueError, match="per-output-channel"):
        quant_matmul(x, q8, bad)


def test_moe_quantized_decode_matches_entry_dequant():
    """MoE generation with int8 expert weights consumed in the scan (the
    Pallas slice path) matches full-precision decoding closely and runs
    end to end."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import generate
    from mlcomp_tpu.ops.quant import quantize_params
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "moe_lm", "vocab_size": 64, "hidden": 128, "layers": 2,
        "heads": 2, "n_experts": 2, "d_ff": 256, "moe_every": 1,
        "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(9).randint(1, 64, (2, 4)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    a = generate(model, q, prompt, 3)                      # entry dequant
    b = generate(model, q, prompt, 3, quant_kernel=True)   # scan int8 path
    assert a.shape == b.shape == (2, 7)
    np.testing.assert_array_equal(np.asarray(a[:, 4]), np.asarray(b[:, 4]))


def test_moe_train_rejects_quantized_experts():
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from mlcomp_tpu.models.moe import MoEBlock
    from mlcomp_tpu.ops.quant import quantize_leaf

    block = MoEBlock(n_experts=2, d_model=128, d_ff=256, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).normal(size=(2, 4, 128)),
                    jnp.float32)
    params = block.init(jax.random.PRNGKey(0), x)["params"]
    qp = dict(params)
    qp["experts_w1"] = quantize_leaf(params["experts_w1"])
    qp["experts_w2"] = quantize_leaf(params["experts_w2"])
    with _pytest.raises(ValueError, match="decode-only"):
        block.apply({"params": qp}, x, train=True)


def test_fuse_decode_params_generation_equal():
    """Round 4: decode_fused (fused qkv + gate_up serving projections)
    generates the SAME greedy tokens as the standard layout, for raw
    weights and for the int8 kernel path; quantize-then-fuse equals
    fuse-then-quantize exactly."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import generate
    from mlcomp_tpu.models.transformer import fuse_decode_params
    from mlcomp_tpu.ops.quant import is_quantized_leaf, quantize_params

    cfg = {
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 2, "heads": 2, "mlp_dim": 512, "dtype": "float32",
    }
    model = create_model(cfg)
    fused_model = create_model({**cfg, "decode_fused": True})
    ids = jnp.asarray(np.random.RandomState(5).randint(1, 128, (2, 8)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    fparams = fuse_decode_params(params)

    attn = fparams["DecoderLayer_0"]["attn"]
    assert "qkv" in attn and "q" not in attn
    assert attn["qkv"]["kernel"].shape == (256, 6, 128)  # H + 2*Hkv = 6
    layer = fparams["DecoderLayer_0"]
    assert "gate_up" in layer and "gate" not in layer
    assert layer["gate_up"]["kernel"].shape == (256, 1024)

    base = generate(model, {"params": params}, ids, 6)
    fused = generate(fused_model, {"params": fparams}, ids, 6)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(fused))

    # quantize-then-fuse == fuse-then-quantize (bit-exact: per-output-
    # channel scales are unaffected by output-axis concatenation)
    qf = fuse_decode_params(quantize_params(params, min_size=1024))
    fq = quantize_params(fparams, min_size=1024)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        qf, fq,
    )
    qkv_leaf = fq["DecoderLayer_0"]["attn"]["qkv"]["kernel"]
    assert is_quantized_leaf(qkv_leaf)
    assert qkv_leaf["q8_scale"].shape == (1, 6, 128)

    base_q = generate(model, {"params": quantize_params(params, min_size=1024)},
                      ids, 6, quant_kernel=True)
    fused_q = generate(fused_model, {"params": fq}, ids, 6, quant_kernel=True)
    np.testing.assert_array_equal(np.asarray(base_q), np.asarray(fused_q))


def test_fused_qkv_stays_int8_through_nonkernel_dequant():
    """The fused qkv/gate_up kernels are recognized by the interception
    path rules: they survive dequantize_nonkernel_params as int8."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.transformer import fuse_decode_params
    from mlcomp_tpu.ops.quant import (
        dequantize_nonkernel_params,
        is_quantized_leaf,
        quantize_params,
    )

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 1, "heads": 2, "mlp_dim": 512, "dtype": "float32",
    })
    ids = jnp.asarray(np.random.RandomState(6).randint(1, 128, (2, 8)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    fq = quantize_params(fuse_decode_params(params), min_size=1024)
    kept = dequantize_nonkernel_params(fq, jnp.float32)
    layer = kept["DecoderLayer_0"]
    assert is_quantized_leaf(layer["attn"]["qkv"]["kernel"])
    assert is_quantized_leaf(layer["gate_up"]["kernel"])
    assert is_quantized_leaf(layer["down"]["kernel"])


def test_sharded_quant_matmul_rejects_untileable_tp_shards():
    """The shard_map island must refuse tp splits that leave non-lane-
    tileable per-device shards, with the actionable message."""
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from mlcomp_tpu.ops.quant import quantize_leaf, sharded_quant_matmul
    from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec.from_config({"dp": 1, "tp": 8}))
    w = jnp.ones((256, 512), jnp.float32)
    leaf = quantize_leaf(w)
    x = jnp.ones((8, 256), jnp.bfloat16)
    # column-parallel: n=512 over tp=8 -> 64-wide shards, not tileable
    with _pytest.raises(ValueError, match="lane-tileable"):
        sharded_quant_matmul(
            x, leaf["q8"], leaf["q8_scale"].reshape(-1), mesh,
            row_parallel=False,
        )
    # row-parallel: m=256 over tp=8 -> 32-wide shards
    with _pytest.raises(ValueError, match="lane-tileable"):
        sharded_quant_matmul(
            x, leaf["q8"], leaf["q8_scale"].reshape(-1), mesh,
            row_parallel=True,
        )


def test_generate_fold_norms_parity_end_to_end():
    """The whole fold-norms interception path (stash -> consume ->
    fused/explicit norm) against the same decode with folding disabled:
    greedy tokens must be IDENTICAL.  This is the end-to-end guard the
    kernel-math test cannot provide — a stash mismatch anywhere in the
    model graph would surface here (or trip the dropped-norm error)."""
    import jax
    import jax.numpy as jnp

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.models.generation import generate
    from mlcomp_tpu.ops.quant import quantize_params
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 128, "hidden": 256,
        "layers": 2, "heads": 2, "mlp_dim": 512, "dtype": "float32",
    })
    assert type(model).fold_norms_eligible
    prompt = jnp.asarray(np.random.RandomState(5).randint(1, 128, (2, 4)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    q = {"params": quantize_params(params, min_size=1024)}
    folded = generate(model, q, prompt, 6, quant_kernel=True)
    try:
        type(model).fold_norms_eligible = False
        plain = generate(model, q, prompt, 6, quant_kernel=True)
    finally:
        type(model).fold_norms_eligible = True
    np.testing.assert_array_equal(np.asarray(folded), np.asarray(plain))


def test_fold_norms_dropped_norm_raises():
    """A skipped RMSNorm whose tensor never reaches a dense-like
    consumer must raise, not silently drop the normalization — both at
    context exit (last norm) and when the next norm overwrites an
    unconsumed stash."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from mlcomp_tpu.models.transformer import RMSNorm
    from mlcomp_tpu.ops.quant import quant_kernel_interception

    class NormThenBreak(nn.Module):
        # the cast between norm and Dense breaks tracer identity
        @nn.compact
        def __call__(self, x):
            h = RMSNorm(dtype=jnp.float32)(x)
            h = h * 2.0
            return nn.Dense(128, use_bias=False)(h)

    m = NormThenBreak()
    x = jnp.ones((2, 128), jnp.float32)
    vs = m.init(jax.random.PRNGKey(0), x)
    with _pytest.raises(RuntimeError, match="silently DROPPED"):
        with quant_kernel_interception(fold_norms=True):
            m.apply(vs, x)

    class TwoNormsFirstDropped(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = RMSNorm(dtype=jnp.float32, name="n1")(x) * 2.0  # dropped
            h = RMSNorm(dtype=jnp.float32, name="n2")(h)
            return nn.Dense(128, use_bias=False)(h)

    m2 = TwoNormsFirstDropped()
    vs2 = m2.init(jax.random.PRNGKey(0), x)
    with _pytest.raises(RuntimeError, match="silently DROPPED"):
        with quant_kernel_interception(fold_norms=True):
            m2.apply(vs2, x)


def test_tp_role_unknown_name_warns_once_and_defaults_column():
    """A kernel-consumable module named outside both Megatron role
    tables takes the column-parallel island, but LOUDLY: one warning per
    name, once (r4 verdict weak #5)."""
    import warnings as _warnings

    from mlcomp_tpu.ops import quant

    quant._warned_tp_roles.discard("my_custom_proj")
    assert quant._tp_role("down") is True
    assert quant._tp_role("qkv") is False
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        assert quant._tp_role("my_custom_proj") is False
        assert quant._tp_role("my_custom_proj") is False  # warned once
    msgs = [str(w.message) for w in rec]
    assert len(msgs) == 1 and "my_custom_proj" in msgs[0]
    assert "_ROW_PARALLEL_NAMES" in msgs[0]
    # known names never warn
    with _warnings.catch_warnings(record=True) as rec2:
        _warnings.simplefilter("always")
        quant._tp_role("out")
        quant._tp_role("lm_head")
    assert not rec2


def test_quant_matmul_prebroadcast_contract_is_explicit():
    """(8, n) scales are accepted ONLY under prebroadcast_scale=True (an
    explicit caller contract — the kernel reads row 0 only, so shape
    inference would silently accept a genuinely non-uniform array)."""
    import jax.numpy as jnp
    import numpy as np_
    import pytest as _pytest

    from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
    from mlcomp_tpu.ops.quant import quantize_leaf

    rs = np_.random.RandomState(0)
    w = jnp.asarray(rs.normal(size=(256, 256)), jnp.float32) * 0.05
    leaf = quantize_leaf(w)
    x = jnp.asarray(rs.normal(size=(4, 256)), jnp.bfloat16)
    s1 = leaf["q8_scale"].reshape(-1)
    s8 = jnp.broadcast_to(s1[None], (8, 256))
    base = quant_matmul(x, leaf["q8"], s1)
    pre = quant_matmul(x, leaf["q8"], s8, prebroadcast_scale=True)
    np_.testing.assert_array_equal(np_.asarray(base), np_.asarray(pre))
    with _pytest.raises(ValueError, match="per-output-channel"):
        quant_matmul(x, leaf["q8"], s8)  # no contract, no acceptance
    with _pytest.raises(ValueError, match="prebroadcast_scale"):
        quant_matmul(x, leaf["q8"], s1, prebroadcast_scale=True)


def test_quant_matmul_fused_norm_matches_explicit():
    """Round 5 glue attack: quant_matmul(norm_scale=...) computes
    rmsnorm in the kernel prologue — must match the explicit
    norm -> cast -> kernel pipeline to f32 tolerance (the mean's
    reduce order may differ), and refuse layouts without full-row
    blocks."""
    import pytest as _pytest

    from mlcomp_tpu.models.transformer import rmsnorm
    from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul

    rs = np.random.RandomState(11)
    for dtype in (jnp.float32, jnp.bfloat16):
        x = jnp.asarray(rs.normal(size=(8, 256)), dtype)
        g = jnp.asarray(rs.normal(size=(256,)).astype(np.float32) + 1.0)
        q8 = jnp.asarray(rs.randint(-127, 127, (256, 128)), jnp.int8)
        scale = jnp.asarray(rs.random(128).astype(np.float32) * 0.01)
        explicit = quant_matmul(
            rmsnorm(x, g, dtype).reshape(-1, 256).astype(jnp.bfloat16),
            q8, scale, interpret=True,
        )
        fused = quant_matmul(
            x, q8, scale, interpret=True, norm_scale=g, norm_dtype=dtype,
        )
        np.testing.assert_allclose(
            np.asarray(explicit, np.float32), np.asarray(fused, np.float32),
            rtol=2e-2, atol=2e-2,  # bf16 matmul; norm reduce order differs
        )
    with _pytest.raises(NotImplementedError, match="full contraction"):
        quant_matmul(
            jnp.zeros((8, 4096), jnp.bfloat16),
            jnp.zeros((4096, 128), jnp.int8),
            jnp.ones((128,), jnp.float32),
            interpret=True, norm_scale=jnp.ones((4096,)), block_d=2048,
        )

"""Int8 weight-only quantization: roundtrip bounds, generation fidelity."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.ops.quant import (
    dequantize_params,
    has_quantized,
    is_quantized_leaf,
    quantize_params,
)


def test_roundtrip_error_bounded():
    w = jnp.asarray(np.random.RandomState(0).normal(size=(128, 64)), jnp.float32)
    q = quantize_params({"w": w}, min_size=1)
    back = dequantize_params(q, jnp.float32)["w"]
    # absmax int8: error <= scale/2 = absmax/254 per channel
    err = np.abs(np.asarray(back) - np.asarray(w))
    bound = np.abs(np.asarray(w)).max(axis=0, keepdims=True) / 254 + 1e-7
    assert (err <= bound).all()


def test_small_and_1d_leaves_pass_through():
    params = {
        "bias": jnp.ones((64,)),
        "norm": jnp.ones((8, 8)),          # below min_size
        "big": jnp.ones((256, 64)),
    }
    q = quantize_params(params)
    assert not is_quantized_leaf(q["bias"]) and q["bias"].dtype == jnp.float32
    assert not is_quantized_leaf(q["norm"])
    assert is_quantized_leaf(q["big"]) and q["big"]["q8"].dtype == jnp.int8
    assert has_quantized(q) and not has_quantized(params)


def test_quantized_generation_close_to_full_precision():
    model = create_model(
        {
            "name": "transformer_lm",
            "vocab_size": 64,
            "hidden": 64,
            "layers": 2,
            "heads": 4,
            "dtype": "float32",
        }
    )
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(1, 64, size=(4, 8)), jnp.int32
    )
    variables = {"params": model.init(jax.random.PRNGKey(0), prompt)["params"]}
    qvars = {"params": quantize_params(variables["params"], min_size=1024)}

    gen = jax.jit(
        partial(generate, model, max_new_tokens=8, weights_dtype=jnp.float32)
    )
    full = np.asarray(gen(variables, prompt=prompt))
    quant = np.asarray(gen(qvars, prompt=prompt))
    assert full.shape == quant.shape == (4, 16)
    # random (untrained) weights make near-ties common; quantization may
    # flip some argmaxes, but the sequences must stay predominantly equal
    agree = (full[:, 8:] == quant[:, 8:]).mean()
    assert agree >= 0.5, f"only {agree:.0%} of tokens agree"
    # and the model's logits under quantized weights stay close
    lf = model.apply(variables, prompt)
    lq = model.apply(
        {"params": dequantize_params(qvars["params"], jnp.float32)}, prompt
    )
    np.testing.assert_allclose(
        np.asarray(lq), np.asarray(lf), atol=0.15, rtol=0.1
    )

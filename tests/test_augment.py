"""On-device augmentation (data/augment.py): op semantics, config
validation, determinism, and the Trainer integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.data.augment import build_augment


def _imgs(b=16, h=16, w=16, c=3, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).uniform(size=(b, h, w, c)), jnp.float32
    )


def test_none_and_validation():
    assert build_augment(None) is None
    assert build_augment({}) is None
    with pytest.raises(ValueError, match="unknown ops"):
        build_augment({"hflpi": True})
    with pytest.raises(ValueError, match="ONE of"):
        build_augment({"crop": 4, "random_resized_crop": True})
    with pytest.raises(ValueError, match="unknown keys"):
        build_augment({"random_resized_crop": {"scael": [0.5, 1.0]}})


def test_hflip_flips_half_and_only_mirrors():
    aug = build_augment({"hflip": True})
    x = _imgs(64)
    out = aug(jax.random.PRNGKey(0), x)
    flipped = np.asarray(
        (out == x[:, :, ::-1, :]).all(axis=(1, 2, 3))
        & ~(out == x).all(axis=(1, 2, 3))
    )
    same = np.asarray((out == x).all(axis=(1, 2, 3)))
    assert (flipped | same).all()  # every row is the image or its mirror
    assert 10 < flipped.sum() < 54  # ~p=0.5


def test_pad_crop_shape_and_content():
    aug = build_augment({"crop": 2})
    x = _imgs(8)
    out = aug(jax.random.PRNGKey(1), x)
    assert out.shape == x.shape
    # every output pixel is either zero padding or from the source image
    vals = set(np.unique(np.asarray(out)).tolist())
    src = set(np.unique(np.asarray(x)).tolist()) | {0.0}
    assert vals <= src


def test_random_resized_crop_shape_dtype():
    aug = build_augment(
        {"random_resized_crop": {"scale": [0.3, 1.0]}, "hflip": True}
    )
    x = _imgs(8).astype(jnp.bfloat16)
    out = aug(jax.random.PRNGKey(2), x)
    assert out.shape == x.shape and out.dtype == x.dtype
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())
    # values stay within the (interpolated) input range
    assert float(out.astype(jnp.float32).max()) <= 1.01
    assert float(out.astype(jnp.float32).min()) >= -0.01


def test_color_ops_and_determinism():
    aug = build_augment({"brightness": 0.4, "contrast": 0.4})
    x = _imgs(8)
    a = aug(jax.random.PRNGKey(3), x)
    b = aug(jax.random.PRNGKey(3), x)
    c = aug(jax.random.PRNGKey(4), x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert a.shape == x.shape


def test_trainer_augment_integration():
    """A jitted train epoch with the full pipeline stays finite and
    actually perturbs the input path (loss differs from no-augment)."""
    from mlcomp_tpu.train.loop import Trainer

    def cfg(augment):
        return {
            "model": {"name": "mnist_cnn", "num_classes": 10},
            "optimizer": {"name": "sgd", "lr": 0.0},  # lr 0: same params
            "loss": "cross_entropy",
            "metrics": ["accuracy"],
            "epochs": 1,
            "seed": 0,
            "augment": augment,
            "data": {
                "train": {
                    "name": "synth_mnist", "n": 64, "batch_size": 32,
                }
            },
        }

    plain = Trainer(cfg(None)).train_epoch()
    auged = Trainer(
        cfg({"hflip": True, "crop": 2, "brightness": 0.2})
    ).train_epoch()
    assert np.isfinite(auged["loss"])
    assert auged["loss"] != plain["loss"]  # pixels really changed


def test_mixup_trains_and_blends():
    """mixup changes the training loss (the blend really happens) and
    composes with grad_accum (partner labels ride the microbatch)."""
    from mlcomp_tpu.train.loop import Trainer

    def cfg(mixup):
        return {
            "model": {"name": "mlp", "num_classes": 4, "hidden": [16]},
            "optimizer": {"name": "sgd", "lr": 0.0},
            "loss": "cross_entropy",
            "metrics": ["accuracy"],
            "epochs": 1,
            "seed": 0,
            "mixup": mixup,
            "data": {
                "train": {
                    "name": "synthetic_classification", "n": 64,
                    "num_classes": 4, "batch_size": 32,
                }
            },
        }

    plain = Trainer(cfg(0.0)).train_epoch()
    mixed = Trainer(cfg(0.4)).train_epoch()
    assert np.isfinite(mixed["loss"])
    assert mixed["loss"] != plain["loss"]

    # grad_accum composes: partner rows travel with their microbatch
    c = cfg(0.4)
    c["grad_accum"] = 2
    acc = Trainer(c).train_epoch()
    assert np.isfinite(acc["loss"])


def test_mixup_refuses_unlabeled():
    from mlcomp_tpu.train.loop import make_train_step

    step = make_train_step(
        lambda out, batch: jnp.mean(out), {}, mixup_alpha=0.2
    )

    class FakeState:
        step = 0

    with pytest.raises(ValueError, match="labeled"):
        step(FakeState(), {"x": jnp.zeros((4, 8))})


def test_mixup_refuses_integer_inputs():
    """Token-id x with labels would silently blend to zeros; refuse."""
    from mlcomp_tpu.train.loop import make_train_step

    step = make_train_step(
        lambda out, batch: jnp.mean(out), {}, mixup_alpha=0.2
    )

    class FakeState:
        step = 0

    with pytest.raises(ValueError, match="float"):
        step(
            FakeState(),
            {"x": jnp.zeros((4, 8), jnp.int32), "y": jnp.zeros(4, jnp.int32)},
        )

"""Multi-host gang scheduling: store slots + end-to-end jax.distributed.

The integration test is the round-1 verdict's 'done' criterion: a DAG
task with ``hosts: 2`` runs under a REAL ``jax.distributed.initialize``
across two localhost child processes, spawned through the worker path
(gang slots, coordinator election, env injection) — no TPU required.
"""

import threading
import time

import pytest

from mlcomp_tpu.dag.schema import DagSpec, ResourceSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.scheduler.worker import Worker


@pytest.fixture()
def store(tmp_db):
    s = Store(tmp_db)
    yield s
    s.close()


def _submit_gang_task(store, hosts=2, executor="noop", args=None, name="mh",
                      max_retries=0):
    dag = DagSpec(
        name="mh", project="t",
        tasks=(TaskSpec(name=name, executor=executor, args=args or {},
                        resources=ResourceSpec(hosts=hosts),
                        max_retries=max_retries),),
    )
    dag_id = store.submit_dag(dag)
    store.set_task_status(dag_id, [name], TaskStatus.QUEUED)
    return dag_id, store.task_rows(dag_id)[0]["id"]


# ---------------------------------------------------------------- store unit


def test_gang_slot_claiming(store):
    _, tid = _submit_gang_task(store, hosts=3)
    a = store.claim_gang_slot("w-a", free_chips=0)
    assert a is not None and a["slot"] == 0 and a["hosts"] == 3
    # one slot per worker per task
    assert store.claim_gang_slot("w-a", free_chips=0) is None
    b = store.claim_gang_slot("w-b", free_chips=0)
    assert b["slot"] == 1
    st = store.gang_state(tid)
    assert not st["filled"]
    c = store.claim_gang_slot("w-c", free_chips=0)
    assert c["slot"] == 2
    assert store.gang_state(tid)["filled"]
    # coordinator publication
    store.publish_coordinator(tid, "10.0.0.1:1234")
    assert store.gang_state(tid)["coordinator"] == "10.0.0.1:1234"


def test_gang_single_host_tasks_unaffected(store):
    """claim_task never hands out hosts>1 tasks; claim_gang_slot never
    hands out hosts=1 tasks."""
    _, tid = _submit_gang_task(store, hosts=2)
    assert store.claim_task("w", free_chips=8) is None
    dag = DagSpec(name="s", project="t",
                  tasks=(TaskSpec(name="one", executor="noop"),))
    d2 = store.submit_dag(dag)
    store.set_task_status(d2, ["one"], TaskStatus.QUEUED)
    got = store.claim_gang_slot("w", free_chips=8)
    assert got is not None and got["task"]["id"] == tid  # the hosts=2 one


def test_gang_release_and_reclaim(store):
    _, tid = _submit_gang_task(store, hosts=2)
    a = store.claim_gang_slot("w-a", free_chips=0)
    assert store.release_gang_slot(tid, a["slot"], "w-a")
    # released slot is claimable again (by anyone, lowest slot first)
    b = store.claim_gang_slot("w-b", free_chips=0)
    assert b["slot"] == 0


def test_gang_dormant_release_refused_when_live(store):
    """ADVICE r2 TOCTOU: once the gang is filled and the task is live, a
    bailing slot holder must NOT be able to release its slot (that would
    launch a gang whose member never comes) — the conditional release
    refuses in one transaction; it succeeds again once the task leaves
    the live states."""
    _, tid = _submit_gang_task(store, hosts=2)
    a = store.claim_gang_slot("w-a", free_chips=0)
    # unfilled gang: dormant release works
    assert store.release_gang_slot_if_dormant(tid, a["slot"], "w-a")
    a = store.claim_gang_slot("w-a", free_chips=0)
    b = store.claim_gang_slot("w-b", free_chips=0)
    # filled + QUEUED (slot 0 about to flip): refused
    assert not store.release_gang_slot_if_dormant(tid, b["slot"], "w-b")
    assert store.start_gang_task(tid, "w-a")
    # filled + IN_PROGRESS: refused
    assert not store.release_gang_slot_if_dormant(tid, b["slot"], "w-b")
    assert store.gang_state(tid)["filled"]
    # task stopped: release allowed again
    assert store.stop_task(tid)
    # (stop clears gang rows; re-gather and check the unfilled case)
    state = store.gang_state(tid)
    assert state["workers"] == {}


def test_gang_cleared_on_requeue_and_stop(store):
    _, tid = _submit_gang_task(store, hosts=2, max_retries=1)
    store.claim_gang_slot("w-a", free_chips=0)
    store.claim_gang_slot("w-b", free_chips=0)
    assert store.start_gang_task(tid, "w-a")
    assert store.requeue_task(tid, expect_worker="w-a")
    assert store.gang_state(tid)["workers"] == {}  # fresh gather next time
    # stop clears too
    store.claim_gang_slot("w-a", free_chips=0)
    assert store.stop_task(tid)
    assert store.gang_state(tid)["workers"] == {}


def test_dead_gang_member_requeues_running_task(store):
    """A slot>0 worker dying AFTER launch wedges the survivors in
    collectives; the reaper must requeue the whole gang task."""
    from mlcomp_tpu.scheduler.supervisor import Supervisor

    _, tid = _submit_gang_task(store, hosts=2, max_retries=1)
    store.heartbeat("w-live", chips=0)
    store.claim_gang_slot("w-live", free_chips=0)   # slot 0
    store.heartbeat("w-dead", chips=0)
    store.claim_gang_slot("w-dead", free_chips=0)   # slot 1
    assert store.start_gang_task(tid, "w-live")
    # w-dead stops heartbeating; w-live stays alive
    time.sleep(0.06)
    store.heartbeat("w-live", chips=0)
    sup = Supervisor(store, worker_timeout_s=0.05)
    sup.tick()
    row = store.task_row(tid)
    assert row["status"] == TaskStatus.QUEUED.value   # requeued, retry spent
    assert store.gang_state(tid)["workers"] == {}     # fresh gather


def test_dead_worker_gang_slots_released(store):
    """Supervisor reap frees slots held by heartbeat-dead workers so a
    half-gathered gang can re-gather."""
    from mlcomp_tpu.scheduler.supervisor import Supervisor

    _, tid = _submit_gang_task(store, hosts=2)
    store.heartbeat("w-dead", chips=0)
    store.claim_gang_slot("w-dead", free_chips=0)
    time.sleep(0.05)
    sup = Supervisor(store, worker_timeout_s=0.01)
    sup.tick()
    assert store.gang_state(tid)["workers"][0] is None


# ------------------------------------------------------------- integration


def _run_worker_until(db_path, stop_evt, errors=None, **kw):
    ws = Store(db_path)
    try:
        w = Worker(ws, isolate=True, load_jax_executors=False,
                   gang_wait_s=90.0, **kw)
        while not stop_evt.is_set():
            if not w.run_once():
                time.sleep(0.2)
    except Exception as e:  # a dead worker thread must be VISIBLE in
        # the test failure, not a silent gang that never fills
        if errors is not None:
            errors.append(e)
        raise
    finally:
        ws.close()


def test_gang_task_runs_under_real_jax_distributed(store, tmp_path):
    """Two workers, one hosts=2 task: each spawns a child; the children
    rendezvous via jax.distributed and assert a 2-process global device
    view, then train one real data-parallel step on the global mesh."""
    helper = tmp_path / "src" / "mh_helper.py"
    helper.parent.mkdir()
    helper.write_text(
        '''
import os

def check(ctx):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    env = {k: v for k, v in os.environ.items()
           if "MLCOMP" in k or k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    assert jax.process_count() == 2, (jax.process_count(), env)
    pid = jax.process_index()
    assert pid == int(os.environ["MLCOMP_TPU_PROCESS_ID"])

    from mlcomp_tpu.parallel.mesh import make_mesh, MeshSpec
    mesh = make_mesh(MeshSpec(dp=len(jax.devices())))
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    n = len(jax.devices())
    x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    gx = jax.make_array_from_callback(x.shape, sharding, lambda i: x[i])
    total = jax.jit(lambda a: jnp.sum(a))(gx)
    expect = float(x.sum())
    assert float(total) == expect, (float(total), expect)
    ctx.log(f"process {pid}: global sum over {n} devices ok")
    return {"processes": jax.process_count(), "devices": n}
'''
    )
    args = {
        "target": "mh_helper:check",
        "code_src": str(helper.parent),
        "code_import": [],
    }
    dag_id, tid = _submit_gang_task(
        store, hosts=2, executor="pyfunc", args=args
    )
    stop_evt = threading.Event()
    threads = []
    for i in range(2):
        wd = tmp_path / f"w{i}"
        wd.mkdir()
        t = threading.Thread(
            target=_run_worker_until,
            args=(store.path, stop_evt),
            kwargs={"name": f"mh-w{i}", "workdir": str(wd), "chips": 0},
            daemon=True,
        )
        t.start()
        threads.append(t)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            row = store.task_row(tid)
            if row["status"] in (TaskStatus.SUCCESS.value,
                                 TaskStatus.FAILED.value):
                break
            time.sleep(0.5)
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
    row = store.task_row(tid)
    logs = "\n".join(l["message"] for l in store.task_logs(tid))
    assert row["status"] == TaskStatus.SUCCESS.value, (
        f"status={row['status']} error={row['error']}\nlogs:\n{logs}"
    )
    import json

    result = json.loads(row["result"])
    assert result == {"processes": 2, "devices": 16}
    # both slots spawned children; only slot 0 wrote the result
    assert "gang slot 0/2" in logs and "gang slot 1/2" in logs


def test_stolen_coordinator_port_gang_recovers(store, tmp_path, monkeypatch):
    """VERDICT r2 next#7: steal the coordinator port in the release→bind
    window.  The slot-0 child must fail fast (CoordinatorBindError
    preflight), the task requeue WITHOUT consuming a retry
    (max_retries=0!), and the re-gathered gang — on a fresh held port —
    succeed."""
    import socket as socket_mod

    helper = tmp_path / "src" / "sp_helper.py"
    helper.parent.mkdir()
    helper.write_text(
        "import jax\n"
        "def check(ctx):\n"
        "    assert jax.process_count() == 2\n"
        "    return {'processes': jax.process_count()}\n"
    )
    args = {
        "target": "sp_helper:check",
        "code_src": str(helper.parent),
        "code_import": [],
    }
    dag_id, tid = _submit_gang_task(
        store, hosts=2, executor="pyfunc", args=args, max_retries=0
    )

    thieves = []
    orig = Worker._spawn_child_inner

    def stealing_spawn(self, claim, gang, ids):
        # first slot-0 spawn only: grab the port the instant the worker
        # releases its hold, exactly the TOCTOU the hardening targets
        if gang and gang["slot"] == 0 and gang.get("sock") and not thieves:
            port = gang["sock"].getsockname()[1]
            gang["sock"].close()
            gang["sock"] = None
            thief = socket_mod.socket()
            thief.setsockopt(
                socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1
            )
            # the slot-1 child may already sit in the closed listener's
            # un-accepted backlog; until the kernel RSTs that orphaned
            # pair the port reads EADDRINUSE (SO_REUSEADDR only bypasses
            # TIME_WAIT) — retry briefly instead of crashing the worker
            # thread (the deflake: this was the under-load failure mode)
            deadline = time.time() + 10.0
            while True:
                try:
                    thief.bind(("", port))
                    break
                except OSError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.05)
            thief.listen(1)
            thieves.append(thief)
        return orig(self, claim, gang, ids)

    monkeypatch.setattr(Worker, "_spawn_child_inner", stealing_spawn)
    stop_evt = threading.Event()
    threads = []
    worker_errors: list = []
    for i in range(2):
        wd = tmp_path / f"w{i}"
        wd.mkdir()
        t = threading.Thread(
            target=_run_worker_until,
            args=(store.path, stop_evt),
            kwargs={"name": f"sp-w{i}", "workdir": str(wd), "chips": 0,
                    "errors": worker_errors},
            daemon=True,
        )
        t.start()
        threads.append(t)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            row = store.task_row(tid)
            if row["status"] in (TaskStatus.SUCCESS.value,
                                 TaskStatus.FAILED.value):
                break
            time.sleep(0.5)
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
        for thief in thieves:
            thief.close()
    row = store.task_row(tid)
    logs = "\n".join(l["message"] for l in store.task_logs(tid))
    diag = (
        f"status={row['status']} retries={row['retries']} "
        f"error={row['error']}\nworker_thread_errors={worker_errors!r}\n"
        f"threads_alive={[t.is_alive() for t in threads]}\nlogs:\n{logs}"
    )
    assert not worker_errors, diag
    assert thieves, f"the steal never fired\n{diag}"
    assert row["status"] == TaskStatus.SUCCESS.value, diag
    assert row["retries"] == 0, row["retries"]
    assert "requeued without consuming a retry" in logs, logs


def test_local_runner_gangs_multihost_dag(tmp_path):
    """`cli dag` path: run_dag_local detects hosts>1, raises the worker
    count, switches to isolated children, and the gang completes."""
    from mlcomp_tpu.scheduler.local import run_dag_local

    helper = tmp_path / "src" / "lr_helper.py"
    helper.parent.mkdir()
    helper.write_text(
        "import jax\n"
        "def check(ctx):\n"
        "    return {'processes': jax.process_count()}\n"
    )
    dag = {
        "info": {"name": "lr-mh", "project": "t"},
        "executors": {
            "mh": {
                "type": "pyfunc",
                "resources": {"hosts": 2},
                "args": {"target": "lr_helper:check",
                         "code_src": str(helper.parent)},
            },
        },
    }
    db = str(tmp_path / "db.sqlite")
    statuses = run_dag_local(
        dag, db_path=db, workdir=str(tmp_path), timeout_s=240.0,
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values()), statuses
    store = Store(db)
    try:
        row = store.task_rows(1)[0]
        # the gang really ran: two jax.distributed processes
        import json

        assert json.loads(row["result"]) == {"processes": 2}
    finally:
        store.close()


def test_gang_train_executor_two_processes(store, tmp_path):
    """The REAL train executor under hosts=2: the Trainer builds its mesh
    over the 16-device global view, the loader feeds via
    make_array_from_callback, metrics are logged once (primary only), and
    the checkpoint lands in storage via a collective orbax save."""
    args = {
        "model": {"name": "mlp", "num_classes": 4, "hidden": [16],
                  "dtype": "float32"},
        "optimizer": {"name": "adam", "lr": 1e-2},
        "loss": "cross_entropy",
        "metrics": ["accuracy"],
        "epochs": 1,
        "data": {
            "train": {"name": "synthetic_classification", "n": 64,
                      "num_classes": 4, "dim": 8, "batch_size": 32},
        },
        "storage_root": str(tmp_path / "storage"),
    }
    dag_id, tid = _submit_gang_task(
        store, hosts=2, executor="train", args=args
    )
    stop_evt = threading.Event()
    threads = []
    for i in range(2):
        wd = tmp_path / f"tw{i}"
        wd.mkdir()
        t = threading.Thread(
            target=_run_worker_until,
            args=(store.path, stop_evt),
            kwargs={"name": f"tr-w{i}", "workdir": str(wd), "chips": 0},
            daemon=True,
        )
        t.start()
        threads.append(t)
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            row = store.task_row(tid)
            if row["status"] in (TaskStatus.SUCCESS.value,
                                 TaskStatus.FAILED.value):
                break
            time.sleep(0.5)
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
    row = store.task_row(tid)
    logs = "\n".join(l["message"] for l in store.task_logs(tid))
    assert row["status"] == TaskStatus.SUCCESS.value, (
        f"status={row['status']} error={row['error']}\nlogs:\n{logs}"
    )
    # metrics logged exactly once per step (slot 1 is non-primary)
    series = store.metric_series(tid, "train/loss")
    steps = [p[0] for p in series]
    assert len(steps) == len(set(steps)) > 0
    # the checkpoint exists on disk
    ckpts = list((tmp_path / "storage").glob("**/checkpoints/*"))
    assert ckpts, "no checkpoint written"


def test_coordinator_ports_avoid_ephemeral_range():
    """r4: gang coordinator ports must come from below the kernel's
    ephemeral floor — an ephemeral coordinator port can be assigned to a
    peer's retrying connect as its SOURCE port, completing a TCP
    self-connect that hangs the gang (the stolen-port test's under-load
    failure, root-caused this round)."""
    from mlcomp_tpu.scheduler.worker import (
        _EPHEMERAL_LO,
        _bind_coordinator_socket,
        _free_port,
    )

    socks = []
    try:
        for _ in range(8):
            s = _bind_coordinator_socket()
            socks.append(s)
            assert s.getsockname()[1] < _EPHEMERAL_LO
        assert _free_port() < _EPHEMERAL_LO
        # distinct ports even while earlier ones stay held
        assert len({s.getsockname()[1] for s in socks}) == 8
    finally:
        for s in socks:
            s.close()

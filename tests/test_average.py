"""Checkpoint averaging (SWA / model soup): exact means, weighting, EMA
preference, step selection, eval-path restorability, CLI surface."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.io.checkpoint import (
    average_checkpoints,
    read_weights,
    save_checkpoint,
)


def _tree(value, bn=0.0, step=1):
    return {
        "params": {"w": jnp.full((4, 4), value, jnp.float32),
                   "b": jnp.full((4,), value * 2, jnp.float32)},
        "model_state": {"batch_stats": {"mean": jnp.full((4,), bn)}},
        "step": step,
    }


def test_average_uniform_and_weighted(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    save_checkpoint(a, _tree(1.0, bn=0.0, step=3), step=3)
    save_checkpoint(b, _tree(3.0, bn=2.0, step=7), step=7)

    out = tmp_path / "avg"
    average_checkpoints([str(a), str(b)], out)
    got = read_weights(out)
    np.testing.assert_allclose(np.asarray(got["params"]["w"]), 2.0)
    np.testing.assert_allclose(np.asarray(got["params"]["b"]), 4.0)
    np.testing.assert_allclose(
        np.asarray(got["model_state"]["batch_stats"]["mean"]), 1.0
    )
    assert got["step"] == 7  # max source step

    out2 = tmp_path / "avg2"
    average_checkpoints([str(a), str(b)], out2, weights=[3, 1])
    got2 = read_weights(out2)
    np.testing.assert_allclose(np.asarray(got2["params"]["w"]), 1.5)


def test_average_prefers_ema_and_step_selection(tmp_path):
    a = tmp_path / "a"
    tree = _tree(1.0)
    tree["ema_params"] = {"w": jnp.full((4, 4), 9.0, jnp.float32),
                          "b": jnp.full((4,), 9.0, jnp.float32)}
    save_checkpoint(a, tree, step=1)
    b = tmp_path / "b"
    save_checkpoint(b, _tree(1.0), step=1)
    save_checkpoint(b, _tree(5.0), step=2)

    out = tmp_path / "avg"
    # EMA from a (9.0) + step-1 of b (1.0) -> 5.0
    average_checkpoints([str(a), f"{b}:1"], out)
    got = read_weights(out)
    np.testing.assert_allclose(np.asarray(got["params"]["w"]), 5.0)


def test_average_validation(tmp_path):
    a = tmp_path / "a"
    save_checkpoint(a, _tree(1.0), step=1)
    with pytest.raises(ValueError, match=">= 2"):
        average_checkpoints([str(a)], tmp_path / "o")
    b = tmp_path / "b"
    save_checkpoint(b, {"params": {"other": jnp.ones((2,))},
                        "model_state": {}, "step": 1}, step=1)
    with pytest.raises(ValueError, match="different parameter structure"):
        average_checkpoints([str(a), str(b)], tmp_path / "o")
    with pytest.raises(ValueError, match="weights"):
        average_checkpoints([str(a), str(a)], tmp_path / "o", weights=[1.0])


def test_averaged_checkpoint_restores_through_eval_path(tmp_path):
    """The averaged artifact must restore via restore_eval_state like any
    train checkpoint (weights-only, EMA-free)."""
    from mlcomp_tpu.io.checkpoint import restore_eval_state
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.optim import create_optimizer
    from mlcomp_tpu.train.state import TrainState, init_model

    model = create_model({"name": "mlp", "hidden": [16], "num_classes": 4})
    x = jnp.zeros((1, 8))
    params, mstate = init_model(model, {"x": x}, jax.random.PRNGKey(0))
    tx = create_optimizer({"name": "sgd", "lr": 0.1})
    state = TrainState.create(model.apply, params, tx, mstate)

    a, b = tmp_path / "a", tmp_path / "b"
    save_checkpoint(
        a, {"params": params, "model_state": mstate, "step": 1}, step=1
    )
    bumped = jax.tree.map(lambda p: p + 2.0, params)
    save_checkpoint(
        b, {"params": bumped, "model_state": mstate, "step": 2}, step=2
    )
    out = tmp_path / "avg"
    average_checkpoints([str(a), str(b)], out)

    restored = restore_eval_state(out, state)
    expect = jax.tree.map(lambda p: p + 1.0, params)
    for e, r in zip(jax.tree.leaves(expect), jax.tree.leaves(restored.params)):
        np.testing.assert_allclose(np.asarray(r), np.asarray(e), rtol=1e-6)


def test_cli_average(tmp_path, capsys):
    from mlcomp_tpu.cli import main

    a, b = tmp_path / "a", tmp_path / "b"
    save_checkpoint(a, _tree(0.0), step=1)
    save_checkpoint(b, _tree(4.0), step=1)
    rc = main([
        "average", str(a), str(b), "--out", str(tmp_path / "avg"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"averaged": 2' in out
    got = read_weights(tmp_path / "avg")
    np.testing.assert_allclose(np.asarray(got["params"]["w"]), 2.0)

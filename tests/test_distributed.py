"""Multi-host distributed backend: hybrid mesh + host-batch assembly.

Single-process CI can only exercise the degenerate paths (one slice, one
process), which is exactly the contract: code written against the hybrid
API must run unchanged from laptop to multi-slice pod.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mlcomp_tpu.parallel.distributed import (
    global_batch_from_host,
    init_distributed,
    make_hybrid_mesh,
    sync_hosts,
)
from mlcomp_tpu.parallel.mesh import MeshSpec


def test_init_distributed_single_process_noop(monkeypatch):
    monkeypatch.delenv("MLCOMP_TPU_COORDINATOR", raising=False)
    monkeypatch.delenv("MLCOMP_TPU_NUM_PROCESSES", raising=False)
    assert init_distributed() is False


def test_hybrid_mesh_single_slice_degenerates_to_ici():
    mesh = make_hybrid_mesh(MeshSpec(dp=4, tp=2), dcn_spec={"dp": 1})
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    assert mesh.axis_names == ("dp", "fsdp", "pp", "sp", "ep", "tp")


def test_hybrid_mesh_rejects_ici_axes_over_dcn():
    with pytest.raises(ValueError, match="may not cross DCN"):
        make_hybrid_mesh(MeshSpec(dp=2, tp=4), dcn_spec={"tp": 2})


def test_hybrid_mesh_rejects_slice_mismatch():
    # CPU devices all sit in one process => one slice; asking for 2 DCN
    # groups must fail loudly instead of silently mislaying the topology.
    with pytest.raises(ValueError, match="slices"):
        make_hybrid_mesh(MeshSpec(dp=8), dcn_spec={"dp": 2})


def test_global_batch_from_host_shards_batch_dim():
    mesh = make_hybrid_mesh(MeshSpec(dp=8))
    batch = {
        "x": np.arange(32, dtype=np.float32).reshape(16, 2),
        "y": np.arange(16, dtype=np.int64),
    }
    g = global_batch_from_host(batch, mesh)
    assert g["x"].shape == (16, 2)
    assert g["x"].sharding.spec == P(("dp", "fsdp"))
    np.testing.assert_array_equal(np.asarray(g["y"]), batch["y"])
    # shards actually live on distinct devices
    assert len({s.device for s in g["x"].addressable_shards}) == 8


def test_global_batch_usable_under_jit():
    mesh = make_hybrid_mesh(MeshSpec(dp=8))
    batch = global_batch_from_host(
        {"x": np.ones((8, 4), np.float32)}, mesh
    )
    out = jax.jit(lambda b: jnp.sum(b["x"]))(batch)
    assert float(out) == 32.0


def test_sync_hosts_single_process_noop():
    sync_hosts("test")  # must not raise or hang

"""fsdp/tp sharding rules on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh
from mlcomp_tpu.parallel.sharding import spec_for, make_sharded_state


def test_spec_for_tp_patterns():
    mesh = make_mesh(MeshSpec(dp=2, tp=4, fsdp=1))
    assert spec_for("layer_0/q/kernel", (512, 8, 64), mesh) == P(None, "tp")
    assert spec_for("layer_0/out/kernel", (8, 64, 512), mesh) == P("tp")
    assert spec_for("layer_0/gate/kernel", (512, 2048), mesh) == P(None, "tp")
    assert spec_for("emb/embedding", (32000, 512), mesh) == P(None, "tp")
    # small leaves stay replicated
    assert spec_for("norm/scale", (512,), mesh) == P()


def test_spec_for_fsdp_largest_dim():
    mesh = make_mesh(MeshSpec(dp=2, fsdp=4))
    assert spec_for("dense/kernel", (256, 1024), mesh) == P(None, "fsdp")
    assert spec_for("dense2/kernel", (1024, 256), mesh) == P("fsdp")
    # tiny params not worth gathering
    assert spec_for("bias", (128,), mesh) == P()


def test_spec_for_tp_plus_fsdp_2d():
    mesh = make_mesh(MeshSpec(dp=1, fsdp=2, tp=4))
    # tp claims the mlp dim, fsdp lands on the other
    assert spec_for("gate/kernel", (512, 2048), mesh) == P("fsdp", "tp")


def test_trainer_fsdp_state_is_sharded_and_trains():
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "mlp", "hidden": [256, 256], "num_classes": 10},
        "optimizer": {"name": "adam", "lr": 1e-3},
        "epochs": 1,
        "mesh": {"dp": 2, "fsdp": 4},
        "data": {
            "train": {"name": "synthetic_classification", "n": 64, "dim": 128,
                      "num_classes": 10, "batch_size": 32},
        },
    }
    tr = Trainer(cfg)
    # at least one param leaf actually sharded over fsdp
    specs = [l.sharding.spec for l in jax.tree.leaves(tr.state.params)]
    assert any("fsdp" in s for s in specs), specs
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])


def test_trainer_tp_transformer_trains():
    from mlcomp_tpu.train.loop import Trainer

    cfg = {
        "model": {"name": "transformer_lm", "vocab_size": 128, "hidden": 64,
                  "layers": 2, "heads": 4, "mlp_dim": 128, "dtype": "float32"},
        "optimizer": {"name": "adam", "lr": 1e-3},
        "loss": "lm_cross_entropy",
        "metrics": [],
        "epochs": 1,
        "mesh": {"dp": 2, "tp": 4},
        "data": {
            "train": {"name": "synthetic_tokens", "n": 32, "seq_len": 16,
                      "vocab_size": 128, "batch_size": 16},
        },
    }
    tr = Trainer(cfg)
    q_kernel = tr.state.params["DecoderLayer_0"]["attn"]["q"]["kernel"]
    assert "tp" in q_kernel.sharding.spec, q_kernel.sharding.spec
    stats = tr.train_epoch()
    assert np.isfinite(stats["loss"])

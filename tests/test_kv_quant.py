"""int8 KV-cache decode: kernel numerics + end-to-end generation parity.

Covers ops/pallas/decode_attention.py (flash-decode over an int8 cache,
interpret mode on CPU) and the ``kv_quant`` wiring in
models/transformer.py / models/moe.py.  The serving rationale and
measured numbers live in the kernel docstring; here we pin correctness:

- kernel vs a dequantize-then-softmax XLA reference (same quantized
  inputs, so the comparison isolates the KERNEL, not the quantization);
- per-row [start, stop) windows including a one-slot and an EMPTY window
  (empty rows must produce exact zeros, the online-softmax guard);
- GQA grouping, dh < 128 zero-padding, and the lane-rounded buffer;
- end-to-end: prefill logits BIT-equal to the bf16-cache path (prefill
  attends fresh K/V in both), decode-step logits within int8 noise, for
  transformer_lm, moe_lm, GQA, and ragged left-padded prompts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate, init_cache
from mlcomp_tpu.ops.pallas.decode_attention import (
    decode_attention,
    quantize_kv,
)


def _reference(q, k8, ks, v8, vs, start, stop, scale):
    b, h, dh = q.shape
    h_kv, l_buf = k8.shape[1], k8.shape[2]
    rep = h // h_kv
    kd = k8.astype(jnp.float32) * ks[..., None]
    vd = v8.astype(jnp.float32) * vs[..., None]
    qg = q.astype(jnp.float32).reshape(b, h_kv, rep, dh)
    logits = jnp.einsum("bhgd,bhld->bhgl", qg, kd) * scale
    slots = jnp.arange(l_buf)
    mask = (slots[None] >= start[:, None]) & (slots[None] < stop[:, None])
    logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    # exact-zero rows for empty windows, like the kernel guard
    p = jax.nn.softmax(logits, axis=-1)
    p = jnp.where(mask[:, None, None, :].any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhgl,bhld->bhgd", p, vd).reshape(b, h, dh)


@pytest.mark.parametrize("h,h_kv,dh", [(8, 8, 128), (8, 2, 128), (4, 1, 64)])
def test_decode_kernel_matches_reference(h, h_kv, dh):
    rng = np.random.default_rng(0)
    b, l_buf = 4, 256
    dhp = max(dh, 128)
    q = jnp.asarray(rng.normal(size=(b, h, dhp)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h_kv, l_buf, dhp)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h_kv, l_buf, dhp)), jnp.float32)
    if dhp != dh:  # emulate the model's zero-padding of small head dims
        zero = jnp.zeros_like(q[..., dh:])
        q = q.at[..., dh:].set(zero)
        k = k.at[..., dh:].set(0.0)
        v = v.at[..., dh:].set(0.0)
    k8, ks = quantize_kv(k)
    v8, vs = quantize_kv(v)
    # windows: full, interior, ONE slot, EMPTY
    start = jnp.asarray([0, 37, 40, 50], jnp.int32)
    stop = jnp.asarray([256, 130, 41, 50], jnp.int32)
    scale = 1.0 / (dh**0.5)
    out = decode_attention(
        q, k8, ks[:, :, None, :], v8, vs[:, :, None, :],
        kv_start=start, kv_stop=stop, scale=scale,
    )
    ref = _reference(q, k8, ks, v8, vs, start, stop, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)
    assert np.abs(np.asarray(out[3])).max() == 0.0  # empty window: zeros


@pytest.mark.parametrize("h,h_kv,dh,s_q", [
    (8, 8, 128, 5), (8, 2, 128, 9), (4, 1, 64, 3), (4, 4, 128, 1),
])
def test_chunk_kernel_matches_reference(h, h_kv, dh, s_q):
    """Multi-query kernel vs a dequant reference with per-query causal
    stops: query j attends [start, stop0 + j)."""
    from mlcomp_tpu.ops.pallas.decode_attention import (
        decode_attention_chunk,
    )

    rng = np.random.default_rng(1)
    b, l_buf = 3, 256
    dhp = max(dh, 128)
    q = jnp.asarray(rng.normal(size=(b, s_q, h, dhp)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h_kv, l_buf, dhp)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h_kv, l_buf, dhp)), jnp.float32)
    if dhp != dh:
        q = q.at[..., dh:].set(0.0)
        k = k.at[..., dh:].set(0.0)
        v = v.at[..., dh:].set(0.0)
    k8, ks = quantize_kv(k)
    v8, vs = quantize_kv(v)
    start = jnp.asarray([0, 17, 40], jnp.int32)
    stop0 = jnp.asarray([200, 60, 41], jnp.int32)  # incl. a 1-slot row
    scale = 1.0 / (dh**0.5)
    out = decode_attention_chunk(
        q, k8, ks[:, :, None, :], v8, vs[:, :, None, :],
        kv_start=start, kv_stop0=stop0, scale=scale,
    )
    # reference: S independent single-token calls at growing stops
    refs = []
    for j in range(s_q):
        refs.append(_reference(
            q[:, j], k8, ks, v8, vs, start, stop0 + j, scale
        ))
    ref = jnp.stack(refs, axis=1)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-2
    )


def test_chunk_kernel_agrees_with_single_token_kernel():
    """S == 1 chunk must match decode_attention exactly (same math,
    same block walk)."""
    from mlcomp_tpu.ops.pallas.decode_attention import (
        decode_attention_chunk,
    )

    rng = np.random.default_rng(2)
    b, h, h_kv, dh, l_buf = 2, 8, 4, 128, 256
    q = jnp.asarray(rng.normal(size=(b, h, dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h_kv, l_buf, dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h_kv, l_buf, dh)), jnp.float32)
    k8, ks = quantize_kv(k)
    v8, vs = quantize_kv(v)
    start = jnp.asarray([0, 11], jnp.int32)
    stop = jnp.asarray([97, 64], jnp.int32)
    a = decode_attention(
        q, k8, ks[:, :, None, :], v8, vs[:, :, None, :],
        kv_start=start, kv_stop=stop,
    )
    c = decode_attention_chunk(
        q[:, None], k8, ks[:, :, None, :], v8, vs[:, :, None, :],
        kv_start=start, kv_stop0=stop,
    )
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(c[:, 0]), atol=1e-5
    )


def test_chunk_kernel_tiles_wide_chunks():
    """Chunks wider than one kernel tile no longer raise (the pre-
    ISSUE-13 NotImplementedError): they run as query-TILED sweeps —
    shape-correct, and each tile bit-identical to calling the kernel
    on that tile with the position-offset stop."""
    from mlcomp_tpu.ops.pallas.decode_attention import (
        CHUNK_MAX_SQ,
        decode_attention_chunk,
    )

    b, h, dh, l_buf = 1, 4, 128, 256
    s = CHUNK_MAX_SQ + 1
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.float32)
    k8 = jnp.asarray(rng.integers(-127, 128, (b, h, l_buf, dh)), jnp.int8)
    sc = jnp.asarray(rng.random((b, h, 1, l_buf)), jnp.float32)
    stop0 = jnp.asarray([l_buf - s + 1], jnp.int32)
    wide = decode_attention_chunk(
        q, k8, sc, k8, sc, kv_stop0=stop0, interpret=True
    )
    assert wide.shape == (b, s, h, dh)
    head = decode_attention_chunk(
        q[:, :CHUNK_MAX_SQ], k8, sc, k8, sc, kv_stop0=stop0,
        interpret=True,
    )
    tail = decode_attention_chunk(
        q[:, CHUNK_MAX_SQ:], k8, sc, k8, sc,
        kv_stop0=stop0 + CHUNK_MAX_SQ, interpret=True,
    )
    assert (np.asarray(head) == np.asarray(wide)[:, :CHUNK_MAX_SQ]).all()
    assert (np.asarray(tail) == np.asarray(wide)[:, CHUNK_MAX_SQ:]).all()


def test_decode_kernel_rejects_bad_scale_shape():
    q = jnp.zeros((1, 4, 128))
    k8 = jnp.zeros((1, 4, 128, 128), jnp.int8)
    ks = jnp.zeros((1, 4, 128), jnp.float32)  # missing the singleton
    with pytest.raises(ValueError, match="scales"):
        decode_attention(q, k8, ks, k8, ks)


def _step_logits(model, variables, prompt, budget=16):
    """Prefill then one decode step; returns (prefill logits, step logits)."""
    b, s = prompt.shape
    cache = init_cache(model, b, budget)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    logits, upd = model.apply(
        {**variables, "cache": cache}, prompt, decode=True, positions=pos,
        mutable=["cache"],
    )
    tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    step, _ = model.apply(
        {**variables, "cache": upd["cache"]}, tok[:, None], decode=True,
        positions=jnp.full((b, 1), s, jnp.int32), mutable=["cache"],
    )
    return np.asarray(logits), np.asarray(step[:, 0])


@pytest.mark.parametrize(
    "name,extra",
    [
        ("transformer_lm", {}),
        ("transformer_lm", {"heads": 4, "kv_heads": 2}),
        ("moe_lm", {"n_experts": 4, "moe_every": 2}),
    ],
)
def test_kv_quant_decode_matches_bf16(name, extra):
    cfg = {"vocab_size": 64, "hidden": 64, "layers": 2, "heads": 4, **extra}
    m_bf = create_model({"name": name, **cfg})
    m_q = create_model({"name": name, **cfg, "kv_quant": True})
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(rng, (2, 7), 1, 64)
    variables = m_bf.init(rng, jnp.zeros((2, 16), jnp.int32))

    pre_bf, step_bf = _step_logits(m_bf, variables, prompt)
    pre_q, step_q = _step_logits(m_q, variables, prompt)
    # prefill never reads the quantized cache: bit-equal
    np.testing.assert_array_equal(pre_bf, pre_q)
    # the decode step reads int8 K/V: within quantization noise
    np.testing.assert_allclose(step_bf, step_q, atol=0.15)


def test_kv_quant_generate_ragged_and_eos():
    cfg = dict(vocab_size=64, hidden=64, layers=1, heads=4)
    m_bf = create_model({"name": "transformer_lm", **cfg})
    m_q = create_model({"name": "transformer_lm", **cfg, "kv_quant": True})
    rng = jax.random.PRNGKey(1)
    prompt = jax.random.randint(rng, (2, 6), 1, 64)
    pm = jnp.array([[False, False, True, True, True, True], [True] * 6])
    variables = m_bf.init(rng, jnp.zeros((2, 12), jnp.int32))
    out_bf = generate(m_bf, variables, prompt, 5, prompt_mask=pm)
    out_q = generate(m_q, variables, prompt, 5, prompt_mask=pm)
    assert out_q.shape == out_bf.shape == (2, 11)
    # random-init greedy argmax can flip on near-ties; require the bulk
    # of tokens to agree rather than bit-equality
    agree = float((out_bf[:, 6:] == out_q[:, 6:]).mean())
    assert agree >= 0.6, f"ragged int8 decode diverged: agreement {agree}"


def test_kv_quant_cache_is_int8():
    m_q = create_model(
        {"name": "transformer_lm", "vocab_size": 64, "hidden": 64,
         "layers": 1, "heads": 4, "kv_quant": True}
    )
    cache = init_cache(m_q, 2, 20)
    leaves = jax.tree.leaves(cache)
    dtypes = {str(x.dtype) for x in leaves}
    assert "int8" in dtypes
    kq = cache["DecoderLayer_0"]["attn"]["cached_key_q"]
    assert kq.dtype == jnp.int8
    assert kq.shape[2] % 128 == 0  # lane-rounded buffer


def test_buffer_length_picker_prefers_fat_blocks():
    """pick_buffer_len must never hand the kernel a divisor-free length:
    2176 = 128*17 would force 17 thin grid steps; the picker pads to the
    next fat-block length instead (r4 profiler finding)."""
    from mlcomp_tpu.ops.pallas.decode_attention import (
        auto_block_kv,
        pick_buffer_len,
    )

    from mlcomp_tpu.ops.pallas.decode_attention import KV_BLOCK_BUDGET

    # the serve-path shape that regressed: hkv=16, dh=128
    lpad = pick_buffer_len(2064, 16, 128)
    blk = auto_block_kv(lpad, 16, 128)
    assert lpad >= 2064 and lpad % 128 == 0
    assert blk >= 384, (lpad, blk)
    # the bench shape keeps its exact length (384 divides 2304 within
    # the ~2MB-per-step budget the late-r4 sweep picked)
    assert pick_buffer_len(2304, 16, 128) == 2304
    assert auto_block_kv(2304, 16, 128) == 384
    # short caches keep the whole buffer in one block
    s = pick_buffer_len(96, 4, 128)
    assert auto_block_kv(s, 4, 128) == s
    # budget respected: K+V block bytes never exceed it
    for l, h, d in ((16384, 8, 128), (4096, 32, 128), (512, 16, 256)):
        lp = pick_buffer_len(l, h, d)
        assert 2 * h * auto_block_kv(lp, h, d) * d <= KV_BLOCK_BUDGET

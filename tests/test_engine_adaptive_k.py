"""Adaptive dispatch depth (ISSUE 13): token bit-equality under any K
schedule, the ladder controller's decision table, warmup precompile of
the K ladder, and the double-buffered paged page-fetch's interpret-mode
bit-exactness vs the rolled fetch and the lax (gather + dense kernel)
reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.dispatch_control import AdaptiveKController, desired_k
from mlcomp_tpu.engine import DecodeEngine, _POISON
from mlcomp_tpu.models import create_model
from mlcomp_tpu.train.state import init_model

_FNS: dict = {}


def _pooled(eng, *key):
    eng._fns = _FNS.setdefault(key, eng._fns)
    return eng


def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


# ------------------------------------------------------- bit-equality


@pytest.mark.parametrize("kv_quant", [False, True])
def test_adaptive_vs_pinned_tokens_bit_equal(kv_quant):
    """The tentpole contract: emitted tokens are identical under ANY K
    schedule — pinned 1, pinned 4, and the adaptive controller's own
    schedule — including a mid-stream admission, at f32 and kv8, with
    a sampling row in the mix (the per-step fold_in RNG is the part a
    per-dispatch split would break)."""
    model, params = _model_and_params(kv_quant)
    rs = np.random.RandomState(2)
    prompts = [rs.randint(1, 64, n).tolist() for n in (5, 9, 13, 7)]

    results = {}
    for name, kw in (
        ("k1", {"steps_per_dispatch": 1}),
        ("k4", {"steps_per_dispatch": 4}),
        ("adaptive", {"steps_per_dispatch": "adaptive",
                      "k_ladder": (1, 2, 4)}),
    ):
        eng = _pooled(
            DecodeEngine(model, {"params": params}, slots=2,
                         prompt_buckets=(16,), max_new_cap=12,
                         seed=7, **kw),
            "eq", kv_quant,
        )
        try:
            # 4 prompts through 2 slots: the later two ADMIT mid-stream
            # while the first two decode (fused admission default);
            # one sampled row exercises the RNG stream
            futs = [
                eng.submit(p, 10,
                           temperature=0.8 if i == 1 else 0.0)
                for i, p in enumerate(prompts)
            ]
            results[name] = [f.result(timeout=300)["ids"] for f in futs]
            if name == "adaptive":
                assert eng.adaptive_k
                assert eng.stats()["k_ladder"] == [1, 2, 4]
        finally:
            eng.close()
    assert results["k1"] == results["k4"], "pinned K changed tokens"
    assert results["adaptive"] == results["k1"], (
        "adaptive schedule changed tokens"
    )


def test_k_switch_streams_bit_equal():
    """The stream-visible version of the mid-stream switch: two parked
    engines decode the same two rows, one under a switching schedule,
    one at K=1 — per-row token streams must match exactly."""
    model, params = _model_and_params()
    rs = np.random.RandomState(4)
    prompts = [rs.randint(1, 64, 6).tolist(), rs.randint(1, 64, 11).tolist()]

    def drive(schedule):
        eng = _pooled(
            DecodeEngine(model, {"params": params}, slots=2,
                         prompt_buckets=(16,), max_new_cap=12, seed=5,
                         steps_per_dispatch=schedule[0]),
            "switch2",
        )
        eng._stop.set()
        eng._queue.put(_POISON)
        eng._thread.join(timeout=30)
        from concurrent.futures import Future

        for i, ids in enumerate(prompts):
            req = {
                "ids": ids, "n_new": 8,
                "temperature": 0.6 if i == 1 else 0.0,
                "top_k": 64, "top_p": 1.0, "eos_id": -1,
                "logprobs": False, "repetition_penalty": 1.0,
                "stream": None, "future": Future(), "t_submit": 0.0,
            }
            eng._start_admission(req)
            while eng._adm is not None:
                eng._run_admission_chunk()
        toks = {0: [], 1: []}
        for k in schedule:
            eng.steps_per_dispatch = int(k)
            before = {
                i: (len(sl.emitted) if sl is not None else None)
                for i, sl in enumerate(eng._host)
            }
            snap = {i: sl for i, sl in enumerate(eng._host)}
            eng._run_dispatch()
            for i, sl in snap.items():
                if sl is None or before[i] is None:
                    continue
                toks[i].extend(t for t, _ in sl.emitted[before[i]:])
            if all(s is None for s in eng._host):
                break
        return toks

    assert drive([1, 1, 4, 2, 8, 8]) == drive([1] * 16)


# --------------------------------------------------------- controller


def test_controller_decision_table():
    ladder = (1, 2, 4, 8)
    # (queue_depth, active, slots) -> desired K
    table = [
        ((0, 0, 8), 1),    # idle: TTFT floor
        ((0, 3, 8), 1),    # free slots, nothing queued: stay joinable
        ((0, 8, 8), 8),    # saturated, empty queue: amortize
        ((1, 8, 8), 2),    # one joiner: one rung up
        ((2, 8, 8), 4),
        ((3, 8, 8), 4),
        ((4, 8, 8), 8),    # deep queue: ladder top
        ((64, 2, 8), 8),
    ]
    for (depth, active, slots), want in table:
        assert desired_k(ladder, depth, active, slots) == want, (
            depth, active, slots
        )


def test_controller_hysteresis_dwell_and_quiesce_snap():
    clock = {"t": 0.0}
    ctl = AdaptiveKController((1, 2, 4, 8), hysteresis=3,
                              min_dwell_s=1.0, clock=lambda: clock["t"])
    assert ctl.k == 1
    # deep queue: needs 3 consecutive votes before switching
    assert ctl.decide(8, 8, 8) == 1
    assert ctl.decide(8, 8, 8) == 1
    assert ctl.decide(8, 8, 8) == 8      # third vote switches
    assert ctl.changes == 1
    # a flapping signal inside the dwell window cannot switch back
    clock["t"] += 0.1
    for _ in range(5):
        assert ctl.decide(1, 8, 8) == 8  # votes pile up, dwell blocks
    clock["t"] += 2.0                    # dwell expires
    assert ctl.decide(1, 8, 8) == 2
    assert ctl.changes == 2
    # full quiesce snaps to the floor immediately, no votes needed
    clock["t"] += 0.01                   # inside the new dwell window
    assert ctl.decide(0, 0, 8) == 1
    assert ctl.changes == 3
    # signals matching the current K reset the candidate votes
    assert ctl.decide(8, 8, 8) == 1
    assert ctl.decide(0, 2, 8) == 1      # desired == current: reset
    assert ctl.decide(8, 8, 8) == 1
    assert ctl.decide(8, 8, 8) == 1
    clock["t"] += 2.0
    assert ctl.decide(8, 8, 8) == 8


def test_controller_bad_ladder_rejected():
    with pytest.raises(ValueError):
        AdaptiveKController(())
    with pytest.raises(ValueError):
        AdaptiveKController((0, 2))
    model, params = _model_and_params()
    with pytest.raises(ValueError, match="adaptive"):
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=8,
                     steps_per_dispatch="sometimes")
    with pytest.raises(ValueError, match="k_ladder"):
        DecodeEngine(model, {"params": params}, slots=2,
                     prompt_buckets=(16,), max_new_cap=8,
                     steps_per_dispatch=4, k_ladder=(1, 4))


# ------------------------------------------------------------- warmup


def test_warmup_precompiles_the_k_ladder():
    """warm_dispatch_fns compiles one plain dispatch per rung (and
    warm_fused_fns one fused program per width per rung), so a
    controller switch mid-serving never compiles on the loop thread."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8,
                       steps_per_dispatch="adaptive", k_ladder=(1, 2))
    try:
        eng._stop.set()
        eng._queue.put(_POISON)
        eng._thread.join(timeout=30)
        assert eng.warm_dispatch_fns() == 2
        assert ("dispatch", 1) in eng._fns and ("dispatch", 2) in eng._fns
        assert eng.warm_dispatch_fns() == 0  # idempotent
        n_fused = eng.warm_fused_fns()
        assert n_fused == 2  # one chunk width x two rungs
        assert eng.warm_fused_fns() == 0
        # pinned engines warm exactly their one K
        eng2 = DecodeEngine(model, {"params": params}, slots=2,
                            prompt_buckets=(16,), max_new_cap=8,
                            steps_per_dispatch=4)
        try:
            eng2._stop.set()
            eng2._queue.put(_POISON)
            eng2._thread.join(timeout=30)
            eng2._fns.update(eng._fns)  # shared pool: no recompiles
            assert eng2.k_ladder == (4,)
            assert eng2.warm_dispatch_fns() == 1
        finally:
            eng2.close()
    finally:
        eng.close()


def test_adaptive_metrics_and_stats_surface():
    """The dispatch_k gauge and changes counter exist from the first
    scrape; a live adaptive engine under a burst moves the gauge."""
    model, params = _model_and_params()
    eng = DecodeEngine(model, {"params": params}, slots=2,
                       prompt_buckets=(16,), max_new_cap=8,
                       steps_per_dispatch="adaptive", k_ladder=(1, 2))
    try:
        snap = eng.metrics.snapshot()
        assert "mlcomp_engine_dispatch_k" in snap
        assert "mlcomp_engine_dispatch_k_changes_total" in snap
        rs = np.random.RandomState(5)
        futs = [
            eng.submit(rs.randint(1, 64, 5).tolist(), 6)
            for _ in range(6)
        ]
        for f in futs:
            f.result(timeout=300)
        st = eng.stats()
        assert st["adaptive_k"] is True
        assert st["steps_per_dispatch"] in (1, 2)
        # the 6-deep burst behind 2 slots must have pushed K up at
        # least once (deep queue -> ladder top), i.e. the gauge moved
        assert st["dispatch_k_changes"] >= 1
    finally:
        eng.close()


# -------------------------------------- double-buffered page fetches


def _paged_fixture(rng, B=2, HKV=2, DH=128, T=128, l_buf=512):
    from mlcomp_tpu.kvpool.allocator import NULL_PAGE, RESERVED_PAGES

    MP = l_buf // T
    P = RESERVED_PAGES + B * MP
    kq = rng.integers(-127, 128, (P, HKV, T, DH)).astype(np.int8)
    vq = rng.integers(-127, 128, (P, HKV, T, DH)).astype(np.int8)
    ks = rng.random((P, HKV, 1, T)).astype(np.float32)
    vs = rng.random((P, HKV, 1, T)).astype(np.float32)
    table = np.full((B, MP), NULL_PAGE, np.int32)
    for r in range(B):
        table[r, : MP - r] = RESERVED_PAGES + r * MP + np.arange(MP - r)
    return kq, vq, ks, vs, table


def _gather_dense_np(pages, table, null_page):
    B, MP = table.shape
    out = np.zeros((B, MP) + pages.shape[1:], pages.dtype)
    for b in range(B):
        for p in range(MP):
            if table[b, p] != null_page:
                out[b, p] = pages[table[b, p]]
    return out


def test_double_buffered_fetch_bit_exact_single_and_chunk():
    """Interpret-mode unit (tentpole 2): the double-buffered page
    fetch is bit-exact vs the rolled fetch AND vs the lax reference
    (page gather feeding the dense kernel) for both paged kernels,
    windows clipping blocks on both sides and NULL pages in range."""
    from mlcomp_tpu.kvpool.allocator import NULL_PAGE
    from mlcomp_tpu.ops.pallas.decode_attention import (
        decode_attention,
        decode_attention_chunk,
        paged_decode_attention,
        paged_decode_attention_chunk,
    )

    rng = np.random.default_rng(0)
    B, HKV, DH, T, l_buf = 2, 2, 128, 128, 512
    kq, vq, ks, vs, table = _paged_fixture(rng, B, HKV, DH, T, l_buf)
    q = rng.standard_normal((B, 2 * HKV, DH)).astype(np.float32)
    start = np.array([64, 0], np.int32)
    stop = np.array([400, 330], np.int32)
    pages = tuple(jnp.asarray(a) for a in (kq, ks, vq, vs))
    jt = jnp.asarray(table)

    o_roll = paged_decode_attention(
        jnp.asarray(q), *pages, jt, kv_start=jnp.asarray(start),
        kv_stop=jnp.asarray(stop), interpret=True, fetch="rolled",
    )
    o_db = paged_decode_attention(
        jnp.asarray(q), *pages, jt, kv_start=jnp.asarray(start),
        kv_stop=jnp.asarray(stop), interpret=True, fetch="double",
    )
    assert (np.asarray(o_roll) == np.asarray(o_db)).all()

    # lax reference: gather the dense view (zeros where NULL), run the
    # DENSE kernel — bit-equality is the paged family's contract
    k8d = _gather_dense_np(kq, table, NULL_PAGE)
    v8d = _gather_dense_np(vq, table, NULL_PAGE)
    ksd = _gather_dense_np(ks, table, NULL_PAGE)
    vsd = _gather_dense_np(vs, table, NULL_PAGE)
    k8 = k8d.transpose(0, 2, 1, 3, 4).reshape(B, HKV, l_buf, DH)
    v8 = v8d.transpose(0, 2, 1, 3, 4).reshape(B, HKV, l_buf, DH)
    ks2 = ksd.transpose(0, 2, 3, 1, 4).reshape(B, HKV, 1, l_buf)
    vs2 = vsd.transpose(0, 2, 3, 1, 4).reshape(B, HKV, 1, l_buf)
    o_lax = decode_attention(
        jnp.asarray(q), jnp.asarray(k8), jnp.asarray(ks2),
        jnp.asarray(v8), jnp.asarray(vs2), kv_start=jnp.asarray(start),
        kv_stop=jnp.asarray(stop), interpret=True,
    )
    assert (np.asarray(o_lax) == np.asarray(o_db)).all()

    # multi-query (chunk) kernels: same three-way equality
    S = 4
    qc = rng.standard_normal((B, S, 2 * HKV, DH)).astype(np.float32)
    stop0 = np.array([397, 327], np.int32)
    oc_roll = paged_decode_attention_chunk(
        jnp.asarray(qc), *pages, jt, kv_start=jnp.asarray(start),
        kv_stop0=jnp.asarray(stop0), interpret=True, fetch="rolled",
    )
    oc_db = paged_decode_attention_chunk(
        jnp.asarray(qc), *pages, jt, kv_start=jnp.asarray(start),
        kv_stop0=jnp.asarray(stop0), interpret=True, fetch="double",
    )
    assert (np.asarray(oc_roll) == np.asarray(oc_db)).all()
    oc_lax = decode_attention_chunk(
        jnp.asarray(qc), jnp.asarray(k8), jnp.asarray(ks2),
        jnp.asarray(v8), jnp.asarray(vs2), kv_start=jnp.asarray(start),
        kv_stop0=jnp.asarray(stop0), interpret=True,
    )
    assert (np.asarray(oc_lax) == np.asarray(oc_db)).all()


def test_wide_chunk_query_tiling_matches_untiled_reference():
    """Tentpole 3: a chunk wider than CHUNK_MAX_SQ runs as query-tiled
    kernel sweeps; each tile's rows must be bit-identical to the
    per-query single-token kernel at the matching causal stop, dense
    and paged alike."""
    from mlcomp_tpu.ops.pallas.decode_attention import (
        CHUNK_MAX_SQ,
        decode_attention,
        decode_attention_chunk,
        paged_decode_attention_chunk,
    )

    rng = np.random.default_rng(1)
    B, HKV, DH, T, l_buf = 1, 2, 128, 128, 512
    kq, vq, ks, vs, table = _paged_fixture(rng, B, HKV, DH, T, l_buf)
    S = CHUNK_MAX_SQ + 8   # forces one full tile + one remainder tile
    H = 2 * HKV
    qc = rng.standard_normal((B, S, H, DH)).astype(np.float32)
    start = np.array([16], np.int32)
    stop0 = np.array([300], np.int32)

    from mlcomp_tpu.kvpool.allocator import NULL_PAGE

    k8d = _gather_dense_np(kq, table, NULL_PAGE)
    v8d = _gather_dense_np(vq, table, NULL_PAGE)
    ksd = _gather_dense_np(ks, table, NULL_PAGE)
    vsd = _gather_dense_np(vs, table, NULL_PAGE)
    k8 = k8d.transpose(0, 2, 1, 3, 4).reshape(B, HKV, l_buf, DH)
    v8 = v8d.transpose(0, 2, 1, 3, 4).reshape(B, HKV, l_buf, DH)
    ks2 = ksd.transpose(0, 2, 3, 1, 4).reshape(B, HKV, 1, l_buf)
    vs2 = vsd.transpose(0, 2, 3, 1, 4).reshape(B, HKV, 1, l_buf)

    wide = decode_attention_chunk(
        jnp.asarray(qc), jnp.asarray(k8), jnp.asarray(ks2),
        jnp.asarray(v8), jnp.asarray(vs2), kv_start=jnp.asarray(start),
        kv_stop0=jnp.asarray(stop0), interpret=True,
    )
    wide = np.asarray(wide)
    assert wide.shape == (B, S, H, DH)
    # per-query reference: query j's causal window is [start, stop0+j)
    # — the single-token kernel at kv_stop = stop0 + j computes the
    # same math (allclose, not bitwise: the two kernels' dots run at
    # different sublane widths, so the fp reduction order may differ)
    for j in (0, 5, CHUNK_MAX_SQ - 1, CHUNK_MAX_SQ, S - 1):
        one = decode_attention(
            jnp.asarray(qc[:, j]), jnp.asarray(k8), jnp.asarray(ks2),
            jnp.asarray(v8), jnp.asarray(vs2),
            kv_start=jnp.asarray(start),
            kv_stop=jnp.asarray(stop0 + j), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(one), wide[:, j], rtol=2e-5, atol=2e-5,
            err_msg=f"query {j}",
        )
    # tile boundaries are exact by construction: the tiled call IS a
    # sequence of plain chunk-kernel calls — slicing the wide output
    # at a tile boundary must equal calling the kernel on that tile
    tile2 = decode_attention_chunk(
        jnp.asarray(qc[:, CHUNK_MAX_SQ:]), jnp.asarray(k8),
        jnp.asarray(ks2), jnp.asarray(v8), jnp.asarray(vs2),
        kv_start=jnp.asarray(start),
        kv_stop0=jnp.asarray(stop0 + CHUNK_MAX_SQ), interpret=True,
    )
    assert (np.asarray(tile2) == wide[:, CHUNK_MAX_SQ:]).all()
    # paged tiled == dense tiled (both fetch modes)
    pages = tuple(jnp.asarray(a) for a in (kq, ks, vq, vs))
    for fetch in ("rolled", "double"):
        pw = paged_decode_attention_chunk(
            jnp.asarray(qc), *pages, jnp.asarray(table),
            kv_start=jnp.asarray(start), kv_stop0=jnp.asarray(stop0),
            interpret=True, fetch=fetch,
        )
        assert (np.asarray(pw) == wide).all(), fetch


def test_paged_fetch_mode_env_and_cost_model():
    import mlcomp_tpu.ops.pallas.decode_attention as da

    assert da.paged_fetch_mode() in ("double", "rolled")
    cm = da.paged_fetch_cost_model(512, 2, 128, 128, window=400)
    assert cm["eligible"]
    assert cm["exposed_block_fetches"]["double"] == 1
    assert cm["exposed_block_fetches"]["rolled"] == cm["live_blocks"]
    bad = da.paged_fetch_cost_model(512 + 128, 2, 128, 96)
    assert bad == {"eligible": False}

"""SLO engine (mlcomp_tpu/obs/slo.py): burn-rate math against
synthetic histories, breach/recover transitions with their
flight-recorder instants, config override + bad-config rejection —
pure host code, no jax."""

import pytest

from mlcomp_tpu.obs.history import MetricsHistory
from mlcomp_tpu.obs.metrics import Registry
from mlcomp_tpu.obs.slo import (
    DEFAULT_SLOS,
    SLOConfigError,
    SLOEngine,
    validate_config,
)
from mlcomp_tpu.utils.trace import Tracer


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_engine(config=None, fast_s=10.0, slow_s=30.0):
    reg = Registry()
    clock = Clock()
    hist = MetricsHistory(reg, interval_s=5.0, clock=clock, start=False)
    cfg = dict(config or {})
    cfg.setdefault("windows", {"fast_s": fast_s, "slow_s": slow_s})
    rec = Tracer()
    slo = SLOEngine(hist, config=cfg, registry=reg, recorder=rec)
    return reg, hist, clock, slo, rec


def tick(hist, clock, slo, dt=5.0):
    clock.t += dt
    hist.sample_now()
    slo.evaluate()


# ------------------------------------------------------------ burn math


def test_availability_burn_rate_math():
    reg, hist, clock, slo, rec = make_engine()
    g = reg.gauge("mlcomp_engine_healthy", "")
    # 2 healthy + 2 unhealthy samples at 5 s ticks: the 30 s slow
    # window holds all four (bad fraction 0.5), the 10 s fast window
    # only the trailing three (bad fraction 2/3) — over a 0.001 budget
    for v in (1, 1, 0, 0):
        g.set(v)
        tick(hist, clock, slo)
    st = slo.status()["slos"]["engine_healthy"]
    assert st["burn_rate"]["fast"] == pytest.approx(2 / 3 / 0.001,
                                                   rel=0.01)
    assert st["burn_rate"]["slow"] == pytest.approx(500.0, rel=0.01)
    assert st["breached"]


def test_disabled_slo_stays_disabled_through_the_engine():
    # regression: SLOEngine validates the RAW config itself; feeding
    # it a pre-validated dict (which drops disabled entries without a
    # marker) used to re-merge the defaults and resurrect them
    reg, hist, clock, slo, rec = make_engine(config={
        "slos": {"per_token_p50": {"enabled": False}},
    })
    assert "per_token_p50" not in slo.slos
    tick(hist, clock, slo)
    assert "per_token_p50" not in slo.status()["slos"]


def test_reject_rate_uses_the_service_counter_on_window_batchers():
    # window/speculative daemons count accepted requests in
    # mlcomp_service_requests_total (the engine family doesn't exist
    # there): one 429 among many successes must be a RATIO, not a
    # denominator-free guaranteed 1.0 breach
    reg, hist, clock, slo, rec = make_engine()
    reg.counter(
        "mlcomp_serving_requests_rejected_total", "",
        labelnames=("reason",),
    ).inc(1, reason="queue_full")
    reg.counter("mlcomp_service_requests_total", "").inc(99)
    tick(hist, clock, slo)
    st = slo.status()["slos"]["reject_rate"]
    assert st["value"] == pytest.approx(0.01)
    assert not st["breached"]


def test_ratio_burn_rate_sums_labelsets_and_idles_at_zero():
    reg, hist, clock, slo, rec = make_engine()
    # no traffic at all: an idle service burns nothing
    tick(hist, clock, slo)
    assert slo.status()["slos"]["reject_rate"]["burn_rate"]["fast"] == 0.0
    rej = reg.counter(
        "mlcomp_serving_requests_rejected_total", "",
        labelnames=("reason",),
    )
    ok = reg.counter("mlcomp_engine_requests_total", "")
    rej.inc(2, reason="queue_full")
    rej.inc(1, reason="concurrency")
    ok.inc(7)
    tick(hist, clock, slo)
    st = slo.status()["slos"]["reject_rate"]
    # 3 rejected of 10 submitted = 0.3 bad fraction / 0.01 budget
    assert st["burn_rate"]["fast"] == pytest.approx(30.0)
    assert st["value"] == pytest.approx(0.3)


def test_latency_quantile_burn_counts_bad_intervals():
    reg, hist, clock, slo, rec = make_engine(config={
        "slos": {"ttft_p95": {"threshold_ms": 100.0, "budget": 0.5}},
    })
    h = reg.histogram(
        "mlcomp_engine_ttft_ms", "", buckets=(10.0, 100.0, 1000.0)
    )
    # interval 1: all fast (p95 <= 100) -> good
    for _ in range(10):
        h.observe(50)
    tick(hist, clock, slo)
    assert not slo.status()["slos"]["ttft_p95"]["breached"]
    # intervals 2+3: all slow -> 2 bad of 3 observed intervals,
    # fraction 2/3 over budget 0.5 -> burn ~1.33 on both windows
    for _ in range(2):
        for _ in range(10):
            h.observe(500)
        tick(hist, clock, slo)
    st = slo.status()["slos"]["ttft_p95"]
    assert st["burn_rate"]["fast"] == pytest.approx(2 / 3 / 0.5, rel=0.01)
    assert st["breached"]
    # the live windowed measurement is the slow p95
    assert st["value"] > 100.0


def test_censored_quantiles_count_bad_and_warn_once():
    # observations past the histogram's largest finite bound live in
    # the implicit +Inf bucket: the materialized quantile clamps to
    # the bound, so a threshold AT/ABOVE it could never fire.  Those
    # censored intervals must count as breaching (fail-safe for an
    # alerting path), and the misconfigured threshold warns once.
    import warnings as w

    reg, hist, clock, slo, rec = make_engine(config={
        "slos": {"ttft_p95": {"threshold_ms": 5000.0, "budget": 0.5}},
    })
    h = reg.histogram("mlcomp_engine_ttft_ms", "", buckets=(10.0, 100.0))
    for _ in range(2):
        for _ in range(10):
            h.observe(999999)  # all mass in +Inf
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            tick(hist, clock, slo)
    st = slo.status()["slos"]["ttft_p95"]
    assert st["breached"], st  # censored intervals counted bad
    # warned exactly once across the two evaluations
    msgs = [str(c.message) for c in caught
            if "largest finite bucket bound" in str(c.message)]
    assert not msgs  # second tick: already warned
    assert "ttft_p95" in slo._censor_warned


def test_intervals_without_observations_do_not_count():
    reg, hist, clock, slo, rec = make_engine()
    reg.histogram("mlcomp_engine_ttft_ms", "", buckets=(10.0, 2500.0))
    for _ in range(4):  # empty intervals only
        tick(hist, clock, slo)
    st = slo.status()["slos"]["ttft_p95"]
    assert st["burn_rate"] == {"fast": 0.0, "slow": 0.0}
    assert not st["breached"]


# ------------------------------------------------- transitions + surfaces


def test_breach_and_recover_transitions_record_instants():
    reg, hist, clock, slo, rec = make_engine(fast_s=10.0, slow_s=30.0)
    g = reg.gauge("mlcomp_engine_healthy", "")
    g.set(0)
    tick(hist, clock, slo)
    assert slo.status()["breached"] == ["engine_healthy"]
    assert slo.status()["slos"]["engine_healthy"]["breaches"] == 1
    # stays breached: no SECOND breach counted, no second instant
    tick(hist, clock, slo)
    assert slo.status()["slos"]["engine_healthy"]["breaches"] == 1
    # healthy again; the bad samples age out of both windows
    g.set(1)
    for _ in range(8):
        tick(hist, clock, slo)
    assert slo.status()["breached"] == []
    names = [e["name"] for e in rec.events]
    assert names.count("slo_breach") == 1
    assert names.count("slo_recover") == 1
    breach = next(e for e in rec.events if e["name"] == "slo_breach")
    assert breach["args"]["slo"] == "engine_healthy"
    assert breach["args"]["burn_fast"] > 1.0


def test_gauges_published_to_registry():
    reg, hist, clock, slo, rec = make_engine()
    reg.gauge("mlcomp_engine_healthy", "").set(0)
    tick(hist, clock, slo)
    text = reg.render()
    assert 'mlcomp_slo_breached{slo="engine_healthy"} 1' in text
    assert 'mlcomp_slo_breaches_total{slo="engine_healthy"} 1' in text
    assert 'mlcomp_slo_burn_rate{slo="engine_healthy",window="fast"}' in text


def test_summary_is_the_healthz_block():
    reg, hist, clock, slo, rec = make_engine()
    tick(hist, clock, slo)
    s = slo.summary()
    assert set(s) == {"evaluations", "breached", "burn_rate"}
    assert set(s["burn_rate"]) == set(DEFAULT_SLOS)


# ------------------------------------------------------------ config


def test_override_merges_over_defaults():
    cfg = validate_config({
        "burn_threshold": 2.0,
        "windows": {"fast_s": 60},
        "slos": {
            "ttft_p95": {"threshold_ms": 500.0},
            "per_token_p50": {"enabled": False},
            "custom_p99": {
                "kind": "latency_quantile",
                "metric": "mlcomp_engine_per_token_ms",
                "q": 0.99, "threshold_ms": 50.0, "budget": 0.02,
            },
        },
    })
    assert cfg["burn_threshold"] == 2.0
    assert cfg["windows"] == {"fast_s": 60.0, "slow_s": 3600.0}
    assert cfg["slos"]["ttft_p95"]["threshold_ms"] == 500.0
    assert cfg["slos"]["ttft_p95"]["q"] == 0.95  # default kept
    assert "per_token_p50" not in cfg["slos"]  # disabled
    assert cfg["slos"]["custom_p99"]["budget"] == 0.02


@pytest.mark.parametrize("bad", [
    "not a dict",
    {"bogus_key": 1},
    {"windows": {"fast_s": -1}},
    {"windows": {"fast_s": 600, "slow_s": 60}},  # fast >= slow
    {"burn_threshold": 0},
    {"slos": "nope"},
    {"slos": {"ttft_p95": {"budget": 2.0}}},
    {"slos": {"ttft_p95": {"no_such_knob": 1}}},
    {"slos": {"fresh": {"budget": 0.1}}},  # new objective, no kind
    {"slos": {"fresh": {"kind": "wat", "budget": 0.1}}},
    {"slos": {"fresh": {"kind": "latency_quantile", "budget": 0.1}}},
    {"slos": {"fresh": {"kind": "ratio", "bad": "x", "total": [],
                        "budget": 0.1}}},
])
def test_bad_config_rejected(bad):
    with pytest.raises(SLOConfigError):
        validate_config(bad)


def test_bad_config_fails_service_construction_shape():
    # the serve layer validates BEFORE spinning up any engine thread;
    # here just pin that SLOEngine itself rejects at construction
    reg = Registry()
    hist = MetricsHistory(reg, start=False)
    with pytest.raises(SLOConfigError):
        SLOEngine(hist, config={"bogus": 1}, registry=reg)

"""LM serving daemon: batcher correctness vs direct generate, bucket
padding exactness, micro-batching of concurrent requests, HTTP round
trip with token auth."""

import json
import time
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.serve import GenerationService, _bucket, load_service
from mlcomp_tpu.train.state import init_model


def _tiny_model():
    return create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })


# share the continuous engine's compiled programs across the DEFAULT-
# config services in this module (the _fns idiom from
# tests/test_engine_fused_admit.py): five tests build the identical
# continuous service, and each was paying the full prefill + insert +
# dispatch compile bill — the single biggest line in the tier-1 time
# budget.  Only the exact default config shares; any engine-visible
# kwarg opts out.
_CONT_FNS: dict = {}


def _service(**kw):
    model = _tiny_model()
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, mstate = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    share = kw == {"batcher": "continuous"}
    kw.setdefault("batch_sizes", (1, 2, 4))
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("max_new_buckets", (4, 8))
    svc = GenerationService(model, {"params": params, **mstate}, **kw)
    if share and svc.engine is not None:
        eng = svc.engine
        eng._fns.update(_CONT_FNS)
        orig_close = svc.close

        def close(*a, **k):
            _CONT_FNS.update(eng._fns)
            return orig_close(*a, **k)

        svc.close = close
    return model, svc


def test_bucket_helper():
    assert _bucket(3, (4, 8), "x") == 4
    assert _bucket(4, (4, 8), "x") == 4
    assert _bucket(5, (4, 8), "x") == 8
    with pytest.raises(ValueError, match="exceeds"):
        _bucket(9, (4, 8), "x")


def test_serve_matches_direct_generate():
    """A bucketed, left-padded, filler-padded service batch must produce
    exactly what a direct generate on the bare prompt produces (greedy,
    so determinism is total)."""
    model, svc = _service(batcher="window")
    try:
        prompt = [3, 14, 15, 9, 2]  # length 5 -> bucket 8, left-padded
        got = svc.generate(prompt, max_new_tokens=4)
        # direct reference: same prompt, no padding at all
        direct = generate(
            model, svc.variables, jnp.asarray([prompt], jnp.int32), 4
        )
        expect = np.asarray(direct)[0, len(prompt):].tolist()
        assert got["ids"] == expect, (got, expect)
        assert got["batched_with"] == 1
    finally:
        svc.close()


def test_serve_batches_concurrent_requests():
    """Concurrent same-bucket requests decode in ONE batch."""
    model, svc = _service(batcher="window", batch_window_ms=200.0)
    try:
        futs = [
            svc.submit([1 + i, 2 + i, 3 + i], max_new_tokens=4)
            for i in range(3)
        ]
        outs = [f.result(timeout=120) for f in futs]
        assert {o["batched_with"] for o in outs} == {3}
        assert svc.stats()["batches"] == 1
        # each row's output equals its own direct generation
        for i, o in enumerate(outs):
            direct = generate(
                model, svc.variables,
                jnp.asarray([[1 + i, 2 + i, 3 + i]], jnp.int32), 4,
            )
            assert o["ids"] == np.asarray(direct)[0, 3:].tolist()
    finally:
        svc.close()


def test_serve_warmup_really_compiles():
    """warmup() must RUN the hot bucket programs (lazy jit means merely
    constructing the wrappers compiles nothing)."""
    _, svc = _service(batcher="window")
    try:
        n = svc.warmup()
        compiled = svc.stats()["compiled"]
        # B=1 and the largest batch, largest prompt bucket, per max_new
        assert n == 4 and len(compiled) == 4
        assert [1, 16, 4] in [list(c) for c in compiled]
        assert [4, 16, 8] in [list(c) for c in compiled]
    finally:
        svc.close()


def test_serve_request_validation():
    _, svc = _service()
    try:
        with pytest.raises(ValueError, match="non-empty"):
            svc.submit([], 4)
        with pytest.raises(ValueError, match="positive"):
            svc.submit([1], 0)
        with pytest.raises(ValueError, match="exceeds"):
            svc.submit([1] * 99, 4)  # over the largest prompt bucket
        with pytest.raises(ValueError, match="exceeds"):
            svc.submit([1], 99)      # over the largest max_new bucket
    finally:
        svc.close()


def test_serve_eos_trimming():
    """eos_id: generated ids stop at (and include) the first EOS."""
    model = _tiny_model()
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, mstate = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    # find what the model greedily emits, then declare THAT id the EOS so
    # the trim path provably fires
    first = int(np.asarray(generate(
        model, {"params": params, **mstate}, prompt[:, :4], 4
    ))[0, 4])
    svc = GenerationService(
        model, {"params": params, **mstate},
        batch_sizes=(1,), prompt_buckets=(8,), max_new_buckets=(4,),
        eos_id=first,
    )
    try:
        out = svc.generate(np.asarray(prompt)[0, :4].tolist(), 4)
        assert out["ids"][-1] == first and len(out["ids"]) <= 4
    finally:
        svc.close()


def test_serve_http_round_trip(tmp_path, monkeypatch):
    """cli-level surface: load_service + HTTP server; token auth; healthz."""
    import socket
    from http.server import ThreadingHTTPServer

    from mlcomp_tpu.serve import serve_http

    model_cfg = {
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    }
    svc = load_service(
        model_cfg, ckpt_dir=None,
        batch_sizes=(1, 2), prompt_buckets=(8,), max_new_buckets=(4,),
    )
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    t = threading.Thread(
        target=serve_http, args=(svc,),
        kwargs={"port": port, "model_name": "tiny"}, daemon=True,
    )
    monkeypatch.setenv("MLCOMP_TPU_SERVE_TOKEN", "tok")
    t.start()
    import time as _t

    for _ in range(50):
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/healthz",
                headers={"Authorization": "Bearer tok"},
            )
            with urllib.request.urlopen(req) as r:
                health = json.loads(r.read())
            break
        except OSError:
            _t.sleep(0.1)
    else:
        raise AssertionError("server never came up")
    assert health["ok"] and health["model"] == "tiny"

    # unauthenticated -> 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
    assert ei.value.code == 403

    body = json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 4}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body,
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer tok"},
    )
    with urllib.request.urlopen(req) as r:
        out = json.loads(r.read())
    assert len(out["ids"]) == 4
    direct = generate(
        _tiny_model(), svc.variables, jnp.asarray([[5, 6, 7]], jnp.int32), 4
    )
    assert out["ids"] == np.asarray(direct)[0, 3:].tolist()

    # malformed request -> 400
    bad = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=b'{"nope": 1}',
        headers={"Authorization": "Bearer tok"},
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(bad)
    assert ei.value.code == 400


def test_serve_sharded_mesh_matches_unsharded():
    """tp-sharded serving (load_service mesh_cfg) must produce the same
    greedy tokens as the single-device service — the SPMD program is a
    layout change, not a math change."""
    from mlcomp_tpu.serve import load_service

    cfg = {"name": "transformer_lm", "vocab_size": 64, "hidden": 32,
           "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32"}
    kw = dict(batch_sizes=(4,), prompt_buckets=(8,), max_new_buckets=(4,))
    plain = load_service(cfg, **kw)
    sharded = load_service(cfg, mesh_cfg={"dp": 4, "tp": 2}, **kw)
    try:
        assert sharded.mesh is not None
        q = sharded.variables["params"]["DecoderLayer_0"]["attn"]["q"][
            "kernel"
        ]
        assert "tp" in q.sharding.spec, q.sharding.spec
        prompt = [3, 14, 15, 9, 2]
        got = sharded.generate(prompt, max_new_tokens=4)
        want = plain.generate(prompt, max_new_tokens=4)
        assert got["ids"] == want["ids"], (got, want)
    finally:
        plain.close()
        sharded.close()


def test_serve_mesh_validates_pallas_layouts_and_batches():
    from mlcomp_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec.from_config({"dp": 2, "tp": 4}))
    model = _tiny_model()
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, mstate = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    variables = {"params": params, **mstate}
    with pytest.raises(ValueError, match="don't divide"):
        GenerationService(model, variables, mesh=mesh, batch_sizes=(1, 2))
    # heads=2 cannot split over tp=4 for the Pallas kernel islands
    with pytest.raises(ValueError, match="must divide heads"):
        GenerationService(
            model, variables, mesh=mesh, batch_sizes=(2,),
            quantize="kernel",
        )
    kv_model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
        "kv_quant": True,
    })
    with pytest.raises(ValueError, match="must divide heads"):
        GenerationService(kv_model, variables, mesh=mesh, batch_sizes=(2,))
    fsdp_mesh = make_mesh(MeshSpec.from_config({"fsdp": 4, "tp": 2}))
    with pytest.raises(ValueError, match="fsdp"):
        GenerationService(
            model, variables, mesh=fsdp_mesh, batch_sizes=(4,),
            quantize="kernel",
        )


def test_serve_sharded_quantized_kernel_matches_single():
    """Round 4: quantize='kernel' + kv_quant compose with a dp×tp mesh —
    the Pallas kernels run inside shard_map islands (quant_matmul with
    Megatron roles, decode_attention with heads over tp) and the greedy
    tokens match the single-device quantized service."""
    from mlcomp_tpu.serve import load_service

    # every tp-sharded dim must stay lane-tileable per device: heads*dh
    # = 256 -> 128/device, mlp 512 -> 256, vocab 256 -> 128
    cfg = {"name": "transformer_lm", "vocab_size": 256, "hidden": 256,
           "layers": 2, "heads": 4, "mlp_dim": 512, "dtype": "float32",
           "kv_quant": True}
    kw = dict(batch_sizes=(4,), prompt_buckets=(8,), max_new_buckets=(4,),
              quantize="kernel")
    plain = load_service(cfg, **kw)
    try:
        want = plain.generate([3, 14, 15, 9, 2], max_new_tokens=4)
    finally:
        plain.close()
    sharded = load_service(cfg, mesh_cfg={"dp": 4, "tp": 2}, **kw)
    try:
        assert sharded.mesh is not None
        got = sharded.generate([3, 14, 15, 9, 2], max_new_tokens=4)
    finally:
        sharded.close()
    assert got["ids"] == want["ids"], (got, want)


def test_rowwise_sampling_matches_static():
    """generation's per-row knob path: greedy rows bit-match the static
    greedy path; neutral knobs (top_k>=V, top_p=1) filter nothing; a
    filtered row only ever emits tokens the filter allows."""
    from mlcomp_tpu.models.generation import (
        process_logits,
        process_logits_rowwise,
        sample_token_rowwise,
    )

    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (4, 32)) * 3.0
    # static vs rowwise with identical per-row knobs
    stat = process_logits(logits, 0.7, 5, 0.9)
    row = process_logits_rowwise(
        logits,
        jnp.full((4,), 0.7),
        jnp.full((4,), 5, jnp.int32),
        jnp.full((4,), 0.9),
    )
    np.testing.assert_allclose(
        np.asarray(stat), np.asarray(row), atol=1e-5
    )
    # greedy rows (t=0) match argmax regardless of other rows' knobs
    t = jnp.asarray([0.0, 1.0, 0.0, 2.0])
    toks = sample_token_rowwise(
        rng, logits, t, jnp.full((4,), 32, jnp.int32), jnp.ones((4,))
    )
    am = jnp.argmax(logits, -1)
    assert int(toks[0]) == int(am[0]) and int(toks[2]) == int(am[2])
    # top_k=1 forces argmax even at high temperature
    toks1 = sample_token_rowwise(
        rng, logits, jnp.full((4,), 5.0), jnp.ones((4,), jnp.int32),
        jnp.ones((4,)),
    )
    np.testing.assert_array_equal(np.asarray(toks1), np.asarray(am))


def test_serve_per_request_knobs_share_program():
    """Mixed-knob requests batch into ONE compiled program; greedy
    requests keep exact determinism while a sampled row differs."""
    model, svc = _service(batcher="window", batch_window_ms=4000.0, batch_sizes=(1, 2))
    try:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as ex:
            f1 = ex.submit(svc.generate, [3, 14, 15, 9, 2], 4)  # greedy
            f2 = ex.submit(
                svc.generate, [7, 3, 44], 4, temperature=5.0, top_k=32
            )
            r1, r2 = f1.result(), f2.result()
        assert r1["batched_with"] == 2 == r2["batched_with"]
        assert len(svc.stats()["compiled"]) == 1  # one program for both
        # the greedy row matches a bare greedy generate exactly
        direct = generate(
            model, svc.variables, jnp.asarray([[3, 14, 15, 9, 2]]), 4
        )
        assert r1["ids"] == np.asarray(direct)[0, 5:].tolist()
    finally:
        svc.close()


def test_serve_rejects_bad_knobs():
    _, svc = _service()
    try:
        with pytest.raises(ValueError, match="temperature"):
            svc.generate([1, 2], 4, temperature=-1.0)
        with pytest.raises(ValueError, match="top_k"):
            svc.generate([1, 2], 4, top_k=0)
        with pytest.raises(ValueError, match="top_p"):
            svc.generate([1, 2], 4, top_p=1.5)
    finally:
        svc.close()


def test_serve_per_request_eos():
    """A request-level eos_id stops ITS row only; the neutral row runs
    to its full budget — both in one batch/program."""
    model, svc = _service(batcher="window", batch_window_ms=4000.0, batch_sizes=(1, 2))
    try:
        # find what greedy emits first so we can use it as the eos
        probe = svc.generate([3, 14, 15, 9, 2], 4)
        first = probe["ids"][0]
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as ex:
            f1 = ex.submit(
                svc.generate, [3, 14, 15, 9, 2], 4, eos_id=first
            )
            f2 = ex.submit(svc.generate, [7, 3, 44], 4)
            r1, r2 = f1.result(), f2.result()
        assert r1["ids"] == [first]  # stopped at its own eos
        assert len(r2["ids"]) == 4   # unaffected neighbor
        assert r1["batched_with"] == 2
    finally:
        svc.close()


def test_serve_logprobs():
    """Requested logprobs align with the emitted ids and equal the
    model's own log-softmax of the greedy logits; requests without the
    flag get no logprobs field."""
    model, svc = _service()
    try:
        prompt = [3, 14, 15, 9, 2]
        r = svc.generate(prompt, 3, logprobs=True)
        assert "logprobs" in r and len(r["logprobs"]) == len(r["ids"])
        assert all(v <= 0.0 for v in r["logprobs"])
        # cross-check the first step against a bare forward
        logits = model.apply(
            svc.variables, jnp.asarray([prompt], jnp.int32)
        )[0, -1]
        expect = float(jax.nn.log_softmax(
            logits.astype(jnp.float32))[r["ids"][0]])
        assert abs(r["logprobs"][0] - expect) < 1e-3
        plain = svc.generate(prompt, 3)
        assert "logprobs" not in plain
    finally:
        svc.close()


def test_serve_repetition_penalty_knob():
    _, svc = _service()
    try:
        r = svc.generate([3, 14, 15, 9, 2], 3, repetition_penalty=1.3)
        assert len(r["ids"]) == 3
        with pytest.raises(ValueError, match="repetition_penalty"):
            svc.generate([1, 2], 3, repetition_penalty=0.0)
    finally:
        svc.close()


def test_serve_moe_sharded_mesh_matches_single():
    """Round 4: moe_lm serves under a dp×ep mesh (experts sharded at
    inference through the decode-shape dense einsum) and produces the
    same greedy tokens as the single-device service."""
    cfg = {"name": "moe_lm", "vocab_size": 64, "hidden": 32, "layers": 2,
           "heads": 2, "n_experts": 4, "moe_every": 2, "dtype": "float32"}
    kw = dict(batch_sizes=(4,), prompt_buckets=(8,), max_new_buckets=(4,))
    plain = load_service(cfg, **kw)
    try:
        want = plain.generate([3, 14, 15, 9, 2], max_new_tokens=4)
    finally:
        plain.close()
    sharded = load_service(cfg, mesh_cfg={"dp": 2, "ep": 4}, **kw)
    try:
        w1 = sharded.variables["params"]["MoELayer_0"]["moe"]["experts_w1"]
        assert "ep" in w1.sharding.spec, w1.sharding.spec
        got = sharded.generate([3, 14, 15, 9, 2], max_new_tokens=4)
    finally:
        sharded.close()
    assert got["ids"] == want["ids"], (got, want)


def test_serve_decode_fused_from_standard_checkpoint(tmp_path):
    """Round 4: `decode_fused: true` in the serve model config restores a
    STANDARD (training-layout) checkpoint and converts the params once —
    greedy tokens equal the unfused service's."""
    from mlcomp_tpu.io.checkpoint import save_checkpoint
    from mlcomp_tpu.serve import load_service

    cfg = {"name": "transformer_lm", "vocab_size": 64, "hidden": 64,
           "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32"}
    model = create_model(cfg)
    prompt = jnp.asarray(np.random.RandomState(4).randint(1, 64, (1, 8)))
    params, mstate = init_model(model, {"x": prompt}, jax.random.PRNGKey(7))
    ckpt = tmp_path / "ckpt"
    save_checkpoint(
        ckpt, {"params": params, "model_state": mstate, "step": 1}, step=1
    )
    kw = dict(batch_sizes=(1,), prompt_buckets=(8,), max_new_buckets=(4,))
    plain = load_service(cfg, ckpt_dir=str(ckpt), **kw)
    try:
        want = plain.generate([3, 14, 15, 9, 2], max_new_tokens=4)
    finally:
        plain.close()
    fused = load_service(
        {**cfg, "decode_fused": True}, ckpt_dir=str(ckpt), **kw
    )
    try:
        fparams = fused.variables["params"]
        assert "qkv" in fparams["DecoderLayer_0"]["attn"]
        got = fused.generate([3, 14, 15, 9, 2], max_new_tokens=4)
    finally:
        fused.close()
    assert got["ids"] == want["ids"], (got, want)
    with pytest.raises(ValueError, match="single-chip"):
        load_service(
            {**cfg, "decode_fused": True}, mesh_cfg={"dp": 8}, **kw
        )


def test_serve_request_count_single_sourced():
    """r4 advisor (low): 'requests' is counted in exactly one place per
    batcher.  Window mode: the service counts.  Continuous mode: the
    engine counts (service increment skipped), warmup dummies excluded,
    and the top-level stats number equals the engine's."""
    _, svc = _service(batcher="window")
    try:
        svc.generate([1, 2, 3], 2)
        svc.generate([1, 2, 3], 2)
        assert svc.stats()["requests"] == 2
    finally:
        svc.close()
    _, svc = _service(batcher="continuous")
    try:
        svc.warmup()  # dummy submissions must not count
        assert svc.stats()["requests"] == 0
        svc.generate([1, 2, 3], 2)
        st = svc.stats()
        assert st["requests"] == 1
        assert st["engine"]["requests"] == 1
    finally:
        svc.close()


def test_window_batcher_defers_head_first_no_starvation():
    """r3/r4 starvation case: a request whose max_new bucket mismatches
    the batch head used to be re-queued at the TAIL, so a sustained
    stream of the other bucket deferred it forever.  Now it heads the
    NEXT batch: wait is bounded by one batch per deferral."""
    from concurrent.futures import Future

    _, svc = _service(batcher="window", batch_sizes=(1, 2),
                      batch_window_ms=50.0)
    # drive the collection policy deterministically: stop the batcher
    # thread, then feed the adversarial arrival order by hand
    svc._stop.set()
    svc._thread.join(timeout=10)
    assert not svc._thread.is_alive()

    def item(name, nb):
        return {"name": name, "bucket_new": nb, "future": Future()}

    b1, a, b2, b3 = item("b1", 4), item("a", 8), item("b2", 4), item("b3", 4)
    for it in (b1, a, b2, b3):
        svc._queue.put(it)
    first = svc._collect()
    assert [i["name"] for i in first] == ["b1", "b2"]  # a deferred
    assert [i["name"] for i in svc._deferred] == ["a"]
    second = svc._collect()
    assert [i["name"] for i in second] == ["a"]  # deferred heads next
    third = svc._collect()
    assert [i["name"] for i in third] == ["b3"]
    # close() fails whatever is still parked in queue/deferred
    svc._deferred = [item("late", 4)]
    late = svc._deferred[0]["future"]
    svc.close()
    assert late.done() and isinstance(late.exception(), RuntimeError)


def test_window_batcher_starvation_stream_end_to_end():
    """The adversarial stream through the real service: the mismatched
    request completes while the stream is still flowing (not last)."""
    import threading as _th

    _, svc = _service(batcher="window", batch_sizes=(1, 2),
                      batch_window_ms=150.0, max_new_buckets=(2, 4))
    done_order = []
    lock = _th.Lock()

    def track(name, fut):
        fut.add_done_callback(
            lambda f: (lock.acquire(), done_order.append(name),
                       lock.release())
        )
        return fut

    try:
        futs = [track("b0", svc.submit([1, 2, 3], 2))]
        futs.append(track("victim", svc.submit([1, 2, 3], 4)))
        for i in range(6):
            futs.append(track(f"b{i + 1}", svc.submit([1, 2, 3], 2)))
            time.sleep(0.05)
        for f in futs:
            f.result(timeout=600)
    finally:
        svc.close()
    assert done_order.index("victim") < len(done_order) - 1, done_order


# ------------------------------------------------- device-profile capture


def _ephemeral_server(svc):
    from mlcomp_tpu.serve import make_http_server

    httpd = make_http_server(svc, "127.0.0.1", 0, "tiny")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_profile_404_on_window_batcher():
    """GET /profile matches /trace semantics on a batcher without a
    drive loop: a 404 with a JSON error body, not a bare 404."""
    _, svc = _service(batcher="window")
    httpd, base = _ephemeral_server(svc)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/profile", timeout=30)
        assert ei.value.code == 404
        body = json.loads(ei.value.read())
        assert "continuous batcher" in body["error"]
        # /trace answers the same way — the two contracts stay aligned
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/trace", timeout=30)
        assert ei.value.code == 404
        assert "continuous batcher" in json.loads(ei.value.read())["error"]
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def test_profile_bad_dispatches_400():
    _, svc = _service(batcher="continuous")
    httpd, base = _ephemeral_server(svc)
    try:
        for bad in ("0", "-3", "nope", "99999"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/profile?dispatches={bad}", timeout=30
                )
            assert ei.value.code == 400, bad
            assert "error" in json.loads(ei.value.read())
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def test_profile_conflict_409_then_completes():
    """A second capture request while one is armed answers 409; the
    armed capture then completes once decode traffic flows and returns
    the attribution JSON over plain HTTP."""
    _, svc = _service(batcher="continuous")
    httpd, base = _ephemeral_server(svc)
    try:
        result = {}

        def arm():
            try:
                with urllib.request.urlopen(
                    f"{base}/profile?dispatches=1", timeout=120
                ) as r:
                    result["code"] = r.status
                    result["body"] = json.loads(r.read())
            except Exception as e:  # surfaced by the main thread
                result["error"] = repr(e)

        th = threading.Thread(target=arm, daemon=True)
        th.start()
        # wait until the engine really holds the armed capture (the
        # HTTP thread needs a moment to reach the engine)
        for _ in range(200):
            if svc.engine._profile is not None:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("capture never armed")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/profile", timeout=30)
        assert ei.value.code == 409
        body = json.loads(ei.value.read())
        assert body["status"] == "profile_busy"

        # traffic completes the window
        gen = json.dumps({"prompt": [3, 4, 5], "max_new_tokens": 4}).encode()
        req = urllib.request.Request(
            f"{base}/generate", data=gen,
            headers={"Content-Type": "application/json"},
        )
        deadline = time.time() + 120
        while th.is_alive() and time.time() < deadline:
            with urllib.request.urlopen(req, timeout=120) as r:
                json.loads(r.read())
        th.join(timeout=30)
        assert result.get("code") == 200, result
        att = result["body"]
        assert att["dispatches"] >= 1
        assert att["device_time_ms"] > 0
        assert att["host_gap_ms"] >= 0
        assert att["kernels"] and att["families"]
        # a capture happened: stats flips to capture-sourced attribution
        dev = svc.engine.stats()["device"]
        assert dev["source"] == "capture"
        assert dev["captures"] == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def test_profile_cancel_disarms_unstarted_capture():
    """The HTTP timeout path: an armed-but-never-started capture (no
    traffic) can be disarmed, failing its future, and a new capture can
    arm afterwards."""
    _, svc = _service(batcher="continuous")
    try:
        fut = svc.profile(dispatches=4)
        assert svc.engine._profile is not None
        assert svc.profile_cancel(fut)
        assert svc.engine._profile is None
        with pytest.raises(RuntimeError, match="cancelled"):
            fut.result(timeout=5)
        fut2 = svc.profile(dispatches=4)  # slot is free again
        assert svc.engine.profile_cancel(fut2)
    finally:
        svc.close()


def test_profile_future_fails_on_close():
    """close() with a capture armed must fail the waiter, not strand
    it."""
    _, svc = _service(batcher="continuous")
    fut = svc.profile(dispatches=2)
    svc.close()
    with pytest.raises(RuntimeError):
        fut.result(timeout=10)

"""Smoke: each BASELINE model-family DAG trains through the executor with
tiny shapes (full-size configs in configs/ are validated for parse only)."""

from pathlib import Path

import pytest

from mlcomp_tpu.dag.parser import parse_dag
from mlcomp_tpu.dag.schema import TaskStatus
from mlcomp_tpu.executors.base import ExecutionContext, run_task
from mlcomp_tpu.scheduler.local import run_dag_local

CONFIG_DIR = Path(__file__).parent.parent / "configs"


@pytest.mark.parametrize("cfg", sorted(CONFIG_DIR.glob("*.yml")))
def test_shipping_configs_parse(cfg):
    dag = parse_dag(cfg)
    assert dag.tasks


def _run_train(args):
    import mlcomp_tpu.executors  # register

    mlcomp_tpu.executors.load_all()
    ctx = ExecutionContext(dag_id=0, task_id=0, task_name="t", args=args)
    ok, result, err = run_task("train", ctx)
    assert ok, err
    return result


def test_resnet_family_trains(tmp_path):
    result = _run_train(
        {
            "model": {"name": "resnet50", "num_classes": 4, "width": 8, "dtype": "float32"},
            "optimizer": {"name": "sgd", "lr": 0.01, "momentum": 0.9},
            "loss": "smoothed_cross_entropy",
            "metrics": ["accuracy"],
            "epochs": 1,
            "data": {
                "train": {
                    "name": "synthetic_images",
                    "n": 16,
                    "height": 32,
                    "width": 32,
                    "num_classes": 4,
                    "batch_size": 8,
                }
            },
            "storage_root": str(tmp_path),
        }
    )
    assert "ckpt_dir" in result


def test_unet_family_trains(tmp_path):
    result = _run_train(
        {
            "model": {"name": "unet", "num_classes": 4, "features": [8, 16], "dtype": "float32"},
            "optimizer": {"name": "adamw", "lr": 1e-3},
            "loss": "pixel_cross_entropy",
            "metrics": ["miou", "pixel_accuracy"],
            "epochs": 1,
            "data": {
                "train": {
                    "name": "synthetic_segmentation",
                    "n": 16,
                    "height": 32,
                    "width": 32,
                    "num_classes": 4,
                    "batch_size": 8,
                }
            },
            "storage_root": str(tmp_path),
        }
    )
    assert result["final"]["train/loss"] > 0


def test_bert_family_trains(tmp_path):
    result = _run_train(
        {
            "model": {
                "name": "bert",
                "vocab_size": 128,
                "hidden": 32,
                "layers": 2,
                "heads": 2,
                "mlp_dim": 64,
                "max_len": 32,
                "num_classes": 2,
                "dtype": "float32",
            },
            "optimizer": {"name": "adamw", "lr": 1e-3},
            "epochs": 1,
            "data": {
                "train": {
                    "name": "synthetic_tokens",
                    "n": 32,
                    "seq_len": 32,
                    "vocab_size": 128,
                    "num_classes": 2,
                    "batch_size": 8,
                }
            },
            "storage_root": str(tmp_path),
        }
    )
    assert "ckpt_dir" in result


def test_grid_search_dag_fans_out(tmp_db, tmp_path):
    statuses = run_dag_local(
        {
            "info": {"name": "grid", "project": "t"},
            "executors": {
                "train": {
                    "type": "train",
                    "grid": {"optimizer.lr": [0.01, 0.001]},
                    "args": {
                        "model": {"name": "mlp", "num_classes": 4, "hidden": [16]},
                        "optimizer": {"name": "adam", "lr": 1e-3},
                        "epochs": 1,
                        "data": {
                            "train": {
                                "name": "synthetic_classification",
                                "n": 64,
                                "num_classes": 4,
                                "dim": 8,
                                "batch_size": 32,
                            }
                        },
                        "storage_root": str(tmp_path),
                    },
                },
                "report": {"type": "noop", "depends": "train"},
            },
        },
        db_path=tmp_db,
        workers=2,
    )
    assert all(s == TaskStatus.SUCCESS for s in statuses.values()), statuses
    assert len(statuses) == 3


def test_longcontext_family_trains(tmp_path):
    """Tiny-shape version of configs/longcontext_lm.yml: ring attention
    over sp with the same config surface."""
    args = {
        "storage_root": str(tmp_path),
        "model": {
            "name": "transformer_lm",
            "vocab_size": 128,
            "hidden": 32,
            "layers": 2,
            "heads": 4,
            "dtype": "float32",
            "seq_parallel": "ring",
        },
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "loss": "lm_cross_entropy",
        "metrics": [],
        "epochs": 1,
        "mesh": {"dp": 2, "sp": 4},
        "data": {
            "train": {
                "name": "synthetic_tokens",
                "n": 8,
                "seq_len": 32,
                "vocab_size": 128,
                "batch_size": 4,
            }
        },
    }
    result = _run_train(args)
    assert result is not None


def test_train_generate_dag(tmp_path):
    """Tiny analog of configs/generate_lm.yml: train a decoder LM, then the
    generate stage restores it via the dependency edge and samples."""
    import numpy as np

    model = {
        "name": "transformer_lm",
        "vocab_size": 32,
        "hidden": 16,
        "layers": 1,
        "heads": 2,
        "dtype": "float32",
    }
    out = tmp_path / "gen.npz"
    dag = {
        "info": {"name": "gen", "project": "t"},
        "executors": {
            "train": {
                "type": "train",
                "stage": "train",
                "args": {
                    "model": model,
                    "optimizer": {"name": "adam", "lr": 1e-3},
                    "loss": "lm_cross_entropy",
                    "metrics": [],
                    "epochs": 1,
                    "data": {
                        "train": {
                            "name": "synthetic_tokens",
                            "n": 16,
                            "seq_len": 16,
                            "vocab_size": 32,
                            "batch_size": 8,
                        }
                    },
                    "storage_root": str(tmp_path / "storage"),
                },
            },
            "sample": {
                "type": "generate",
                "stage": "infer",
                "depends": "train",
                "args": {
                    "model": model,
                    "data": {
                        "infer": {
                            "name": "synthetic_tokens",
                            "n": 8,
                            "seq_len": 8,
                            "vocab_size": 32,
                            "batch_size": 8,
                        }
                    },
                    "max_new_tokens": 4,
                    "out": str(out),
                },
            },
        },
    }
    statuses = run_dag_local(dag, db_path=str(tmp_path / "db.sqlite"),
                             workdir=str(tmp_path))
    assert all(s == TaskStatus.SUCCESS for s in statuses.values()), statuses
    ids = np.load(out)["ids"]
    assert ids.shape == (8, 12)

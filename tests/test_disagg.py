"""Disaggregated prefill/decode: KV pages as the transfer currency.

The acceptance contract: a prompt prefilled on a ``prefill_only``
engine and imported into a paged decode engine emits tokens AND
logprobs bit-identical to the monolithic engine — across cache
families (f32 + kv8) and pipeline depths — while a truncated or
mismatched handoff is rejected TYPED with zero pages, leases, or
slots touched, and the fleet router brokers the two-hop path end to
end over real HTTP."""

import functools
import json
import queue
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.engine import DecodeEngine
from mlcomp_tpu.kvpool.transfer import (
    HandoffError,
    decode_handoff,
    encode_handoff,
    rows_to_page_tiles,
)
from mlcomp_tpu.models import create_model
from mlcomp_tpu.serve import BackpressureError, GenerationService
from mlcomp_tpu.train.state import init_model


@functools.lru_cache(maxsize=None)
def _model_and_params(kv_quant=False, seed=0):
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 64,
        "layers": 2, "heads": 2, "mlp_dim": 128, "dtype": "float32",
        "kv_quant": kv_quant,
    })
    prompt = jnp.asarray(np.random.RandomState(seed).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(seed))
    return model, params


IDS_A = [3, 14, 15, 9, 2, 6, 53, 58, 9, 7]
IDS_B = [7, 3, 44, 5, 6]

# share compiled programs across same-geometry engines: prefill-only
# engines compile a subset of the dense family (chunk/init/capture),
# paged engines their own dispatch/insert/import family
_FNS: dict = {}


def _engine(kind, kv_quant=False, **kw):
    model, params = _model_and_params(kv_quant)
    kw.setdefault("prompt_buckets", (16,))
    kw.setdefault("max_new_cap", 12)
    kw.setdefault("steps_per_dispatch", 2)
    kw.setdefault("prefill_chunk", 4)
    if kind == "prefill":
        kw["prefill_only"] = True
        kw.setdefault("slots", 1)
    else:
        kw.setdefault("slots", 2)
        kw["kv_layout"] = "paged"
    eng = DecodeEngine(model, {"params": params}, **kw)
    pool = _FNS.setdefault((kind, kv_quant), {})
    eng._fns.update(pool)
    eng._fns_pool = pool
    return eng


def _close(eng):
    if hasattr(eng, "_fns_pool"):
        eng._fns_pool.update(eng._fns)
    eng.close()


def _result_key(r):
    return (r["ids"], r.get("logprobs"))


# ------------------------------------------------------------ wire format


def test_wire_roundtrip():
    meta = {"s_bucket": 16, "ids": [1, 2, 3], "n_new": 4}
    logits = np.arange(8, dtype=np.float32).reshape(1, 8)
    payloads = [
        np.random.default_rng(0).standard_normal((3, 4, 2, 5)).astype(
            np.float32
        ),
        np.random.default_rng(1).integers(
            -128, 127, (3, 4, 2), dtype=np.int8
        ),
    ]
    blob = encode_handoff(meta, logits, payloads)
    m, lg, pl = decode_handoff(blob)
    assert m["s_bucket"] == 16 and m["ids"] == [1, 2, 3]
    assert m["version"] == 1
    np.testing.assert_array_equal(lg, logits)
    assert len(pl) == 2
    np.testing.assert_array_equal(pl[0], payloads[0])
    np.testing.assert_array_equal(pl[1], payloads[1])
    assert pl[1].dtype == np.int8


def test_wire_bf16_leaves_roundtrip():
    import ml_dtypes

    bf = np.asarray(
        np.random.default_rng(2).standard_normal((2, 4, 3)),
        ml_dtypes.bfloat16,
    )
    blob = encode_handoff({"x": 1}, np.zeros((1, 4), np.float32), [bf])
    _, _, (out,) = decode_handoff(blob)
    assert out.dtype == bf.dtype
    np.testing.assert_array_equal(
        out.view(np.uint16), bf.view(np.uint16)
    )


def test_wire_typed_rejects():
    blob = encode_handoff(
        {"s_bucket": 16}, np.zeros((1, 8), np.float32),
        [np.zeros((2, 4, 2), np.float32)],
    )
    # every truncation point — inside the magic, the header length,
    # the header, each array — rejects typed, as does trailing junk
    for cut in (0, 4, 10, 30, len(blob) - 1):
        with pytest.raises(HandoffError):
            decode_handoff(blob[:cut])
    with pytest.raises(HandoffError):
        decode_handoff(blob + b"x")
    with pytest.raises(HandoffError):
        decode_handoff(b"NOTMAGIC" + blob[8:])
    with pytest.raises(HandoffError):
        decode_handoff(json.dumps({"version": 99}).encode())
    with pytest.raises(HandoffError):
        decode_handoff("not bytes")


def test_rows_to_page_tiles():
    a = np.arange(2 * 8 * 3, dtype=np.float32).reshape(1, 8, 6)[:, :, :3]
    a = np.ascontiguousarray(a)  # (1, 8, 3), slot axis 1
    tiles = rows_to_page_tiles(a, 1, 4)
    assert tiles.shape == (2, 4, 3)
    np.testing.assert_array_equal(tiles[0], a[0, :4])
    np.testing.assert_array_equal(tiles[1], a[0, 4:])
    with pytest.raises(ValueError):
        rows_to_page_tiles(a, 1, 3)  # 8 % 3 != 0


# --------------------------------------------------- engine export/import


def _export_blob(kv_quant, ids, n_new, **req_kw):
    pre = _engine("prefill", kv_quant)
    try:
        res = pre.submit(ids, n_new, **req_kw).result(timeout=300)
        st = pre.stats()
        assert st["handoffs_exported"] == 1, st
        assert st["kv_pages_exported"] == res["pages"] > 0, (st, res)
        assert st["handoff_bytes_exported"] == len(res["handoff"]), st
    finally:
        _close(pre)
    return res["handoff"]


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("depth", [1, 2])
def test_import_bit_identical_to_monolithic(kv_quant, depth):
    """The acceptance bar: decode on imported pages emits tokens AND
    logprobs bit-identical to the monolithic paged engine, for both
    cache families, at pipeline depth 1 and 2."""
    mono = _engine("decode", kv_quant, pipeline_depth=depth)
    try:
        r_mono = mono.submit(IDS_A, 8, logprobs=True).result(timeout=300)
    finally:
        _close(mono)
    blob = _export_blob(kv_quant, IDS_A, 8, logprobs=True)
    dec = _engine("decode", kv_quant, pipeline_depth=depth)
    try:
        r_imp = dec.import_pages(blob).result(timeout=300)
        st = dec.stats()
    finally:
        _close(dec)
    assert _result_key(r_imp) == _result_key(r_mono)
    assert st["handoffs_imported"] == 1
    assert st["kv_pages_imported"] > 0
    assert st["handoff_rejects"] == 0


def test_import_streams_and_interleaves_with_local_traffic():
    """An import admits mid-stream next to a locally-admitted request;
    both finish exact, and the imported request streams its tokens."""
    mono = _engine("decode")
    try:
        r_a = mono.submit(IDS_A, 8).result(timeout=300)
        r_b = mono.submit(IDS_B, 6).result(timeout=300)
    finally:
        _close(mono)
    blob = _export_blob(False, IDS_A, 8)
    dec = _engine("decode")
    try:
        fb = dec.submit(IDS_B, 6)
        toks: "queue.Queue" = queue.Queue()
        fa = dec.import_pages(blob, stream=toks)
        r_imp, r_loc = fa.result(timeout=300), fb.result(timeout=300)
        streamed = []
        while True:
            t = toks.get(timeout=30)
            if t is None:
                break
            streamed.append(t)
    finally:
        _close(dec)
    assert r_imp["ids"] == r_a["ids"]
    assert r_loc["ids"] == r_b["ids"]
    assert [t["token"] for t in streamed] == r_a["ids"]


def test_prefill_only_blob_deterministic_across_cache_hit():
    """The prefill core keeps its prefix cache: a repeated prompt
    prefills from the cache (cache_hit_tokens > 0) and the exported
    blob is BIT-IDENTICAL to the cold one — the cache changes the
    bill, not the pages."""
    from mlcomp_tpu.cache import PrefixKVCache

    model, params = _model_and_params(False)
    cache = PrefixKVCache(max_bytes=1 << 20)
    pre = DecodeEngine(
        model, {"params": params}, slots=1, prompt_buckets=(16,),
        max_new_cap=12, steps_per_dispatch=2, prefill_chunk=4,
        prefill_only=True, prefix_cache=cache,
    )
    try:
        cold = pre.submit(IDS_A, 8).result(timeout=300)
        cache.flush()
        warm = pre.submit(IDS_A, 8).result(timeout=300)
    finally:
        pre.close()
    assert cold["cache_hit_tokens"] == 0
    assert warm["cache_hit_tokens"] > 0
    # logits and every REAL row are bit-identical; only the first
    # page's pad rows (< start_pad, masked out of every attention
    # read) legitimately differ — cold prefill computes don't-care
    # pad K/V there, the cache-hit assembly leaves zeros — plus the
    # per-request header fields (rseed, trace id)
    m_c, lg_c, pl_c = decode_handoff(cold["handoff"])
    m_w, lg_w, pl_w = decode_handoff(warm["handoff"])
    np.testing.assert_array_equal(lg_w, lg_c)
    for a, b in zip(pl_w, pl_c):
        np.testing.assert_array_equal(
            a[1:].view(np.uint8), b[1:].view(np.uint8)
        )
    for k in ("s_bucket", "start_pad", "page_tokens", "n_pages",
              "ids", "leaves"):
        assert m_w[k] == m_c[k], k
    # and the decode-side proof that the pad rows are immaterial:
    # both blobs decode bit-identically
    outs = []
    for blob in (cold["handoff"], warm["handoff"]):
        dec = _engine("decode")
        try:
            outs.append(
                _result_key(dec.import_pages(blob).result(timeout=300))
            )
        finally:
            _close(dec)
    assert outs[0] == outs[1]


def test_import_registers_pages_for_cow_sharing():
    """Imported pages land in the device prefix-page registry exactly
    as if this replica had prefilled them: a later LOCAL admission of
    the same prompt maps them copy-on-write (registry hit) and decodes
    bit-identically."""
    blob = _export_blob(False, IDS_A, 8)
    dec = _engine("decode", kv_pages=48)
    try:
        r_imp = dec.import_pages(blob).result(timeout=300)
        r_loc = dec.submit(IDS_A, 8).result(timeout=300)
        st = dec.stats()
    finally:
        _close(dec)
    assert r_loc["ids"] == r_imp["ids"]
    assert st["kv_registry_hit_tokens"] > 0, st


def test_import_into_near_full_pool_rejects_typed():
    """A service whose pool cannot hold the import's pages fast-fails
    the handoff with the typed ``no_free_pages`` backpressure verdict
    — before anything was allocated (pool stats unchanged)."""
    model, params = _model_and_params(False)
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
        prefill_chunk=4, kv_layout="paged", kv_page_tokens=4,
        max_slots=2, kv_pages=9, phase="decode",
    )
    try:
        blob = _export_blob(False, IDS_A, 8)
        # a live stream on a DIFFERENT prompt holds most of the tight
        # pool (same prompt would let the import map the registry's
        # pages COW and sail through)
        other = [5, 8, 21, 33, 41, 17, 29, 60, 11, 13]
        q: "queue.Queue" = queue.Queue()
        fut = svc.submit(other, 8, stream=q)
        q.get(timeout=300)  # decoding: its pages are held
        free_before = svc.engine._pool.stats()["pages_free"]
        with pytest.raises(BackpressureError) as ei:
            svc.import_pages(blob)
        assert ei.value.reason == "no_free_pages"
        assert svc.engine._pool.stats()["pages_free"] == free_before
        fut.result(timeout=300)
    finally:
        svc.close()


def test_truncated_import_zero_leaks_then_recovers():
    """Chaoscheck scenario 10's engine half: a blob truncated at any
    point (the prefill replica died mid-transfer) is rejected TYPED
    with zero pages/leases touched and the reject counted; the intact
    blob then imports fine on the same engine."""
    blob = _export_blob(False, IDS_A, 8)
    dec = _engine("decode")
    try:
        pool = dec._pool
        free0 = pool.stats()["pages_free"]
        for cut in (6, len(blob) // 2, len(blob) - 1):
            with pytest.raises(HandoffError):
                dec.import_pages(blob[:cut])
        # geometry mismatch is typed too: a foreign page quantum
        meta, lg, pl = decode_handoff(blob)
        bad = dict(meta, page_tokens=8)
        bad.pop("arrays", None)
        with pytest.raises(HandoffError):
            dec.import_pages(encode_handoff(bad, lg, pl))
        # ... and so is a prompt past this engine's largest bucket
        # (a hand-rolled topology with diverging prompt_buckets)
        toolong = dict(meta, ids=list(range(1, 25)), s_bucket=32,
                       start_pad=8)
        toolong.pop("arrays", None)
        with pytest.raises(HandoffError):
            dec.import_pages(encode_handoff(toolong, lg, pl))
        st = pool.stats()
        assert st["pages_free"] == free0, st
        assert dec.stats()["handoff_rejects"] == 5
        r = dec.import_pages(blob).result(timeout=300)
        assert len(r["ids"]) == 8
        assert dec.stats()["handoffs_imported"] == 1
    finally:
        _close(dec)


def test_prefill_only_constructor_contract():
    model, params = _model_and_params(False)
    kw = dict(slots=1, prompt_buckets=(16,), max_new_cap=12,
              prefill_chunk=4)
    for bad in (
        {"spec_k": 2},
        {"kv_layout": "paged"},
        {"kv_pages": 8},
        {"max_slots": 2},
    ):
        with pytest.raises(ValueError):
            DecodeEngine(model, {"params": params}, prefill_only=True,
                         **{**kw, **bad})
    # export pages must tile the chunk geometry
    with pytest.raises(ValueError):
        DecodeEngine(model, {"params": params}, prefill_only=True,
                     kv_page_tokens=3, **kw)
    pre = _engine("prefill")
    try:
        with pytest.raises(ValueError):
            pre.submit(IDS_A, 4, stream=queue.Queue())
        assert pre.warm_dispatch_fns() == 0
        assert pre.warm_export_fns() > 0
    finally:
        _close(pre)


def test_import_needs_paged_layout():
    model, params = _model_and_params(False)
    eng = DecodeEngine(
        model, {"params": params}, slots=2, prompt_buckets=(16,),
        max_new_cap=12, steps_per_dispatch=2, prefill_chunk=4,
    )
    try:
        with pytest.raises(ValueError, match="paged"):
            eng.import_pages(b"whatever")
    finally:
        eng.close()


# ------------------------------------------------------------- HTTP layer


@functools.lru_cache(maxsize=None)
def _tiny_model_and_params():
    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    return model, params


_TINY_FNS: dict = {}


def _tiny_service(phase, **kw):
    from mlcomp_tpu.serve import make_http_server

    model, params = _tiny_model_and_params()
    if phase in ("decode", "both"):
        kw.setdefault("kv_layout", "paged")
        kw.setdefault("max_slots", 2)
        kw.setdefault("kv_pages", 24)
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
        prefill_chunk=8, phase=phase, **kw,
    )
    pool = _TINY_FNS.setdefault(
        (phase if phase == "prefill" else "decode"), {}
    )
    svc.engine._fns.update(pool)
    httpd = make_http_server(svc, "127.0.0.1", 0, "disagg")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    return svc, httpd, base, pool


def _post(url, body, ctype="application/json", timeout=120):
    data = body if isinstance(body, (bytes, bytearray)) else (
        json.dumps(body).encode()
    )
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": ctype},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def test_serve_phase_split_http_end_to_end():
    """POST /prefill on a prefill daemon -> handoff blob; POST /import
    on a decode daemon -> tokens bit-identical to the monolithic
    daemon's /generate; a truncated blob -> typed 400 bad_handoff;
    /generate at the prefill daemon -> 409 wrong_phase; /healthz
    surfaces the role on both."""
    prompt = [9, 10, 11, 12, 13, 14, 15, 16, 17, 3]
    mono = _tiny_service("both")
    try:
        code, body, _ = _post(
            mono[2] + "/generate",
            {"prompt": prompt, "max_new_tokens": 4, "logprobs": True},
        )
        assert code == 200, body
        r_mono = json.loads(body)
        _TINY_FNS["decode"].update(mono[0].engine._fns)
    finally:
        mono[1].shutdown()
        mono[1].server_close()
        mono[0].close()

    pre = _tiny_service("prefill", kv_layout="dense")
    dec = _tiny_service("decode")
    try:
        code, hz, _ = _post(pre[2] + "/generate",
                            {"prompt": prompt, "max_new_tokens": 4})
        assert code == 409 and json.loads(hz)["status"] == "wrong_phase"
        with urllib.request.urlopen(pre[2] + "/healthz",
                                    timeout=30) as r:
            assert json.loads(r.read())["phase"] == "prefill"
        with urllib.request.urlopen(dec[2] + "/healthz",
                                    timeout=30) as r:
            assert json.loads(r.read())["phase"] == "decode"

        code, blob, hdrs = _post(
            pre[2] + "/prefill",
            {"prompt": prompt, "max_new_tokens": 4, "logprobs": True},
        )
        assert code == 200, blob
        assert hdrs["Content-Type"] == "application/octet-stream"
        sidecar = json.loads(hdrs["x-mlcomp-handoff"])
        assert sidecar["pages"] > 0
        assert sidecar["prefill_tokens"] == len(prompt)

        code, body, _ = _post(
            dec[2] + "/import", blob, ctype="application/octet-stream",
        )
        assert code == 200, body
        r_imp = json.loads(body)
        assert r_imp["ids"] == r_mono["ids"]
        assert r_imp["logprobs"] == r_mono["logprobs"]

        code, body, _ = _post(
            dec[2] + "/import", blob[: len(blob) - 40],
            ctype="application/octet-stream",
        )
        assert code == 400, body
        assert json.loads(body)["status"] == "bad_handoff"
        assert dec[0].engine.stats()["handoff_rejects"] == 1
    finally:
        for svc, httpd, _base, pool in (pre, dec):
            pool.update(svc.engine._fns)
            httpd.shutdown()
            httpd.server_close()
            svc.close()


def test_router_two_hop_handoff():
    """The fleet path end to end: a router fronting one prefill and
    one decode replica brokers /generate as prefill -> pages ->
    import, with tokens bit-identical to the monolithic daemon,
    handoffs counted, and upstream connections REUSED (keep-alive
    pool)."""
    from types import SimpleNamespace

    from mlcomp_tpu.fleet import (
        CallableLauncher,
        ReplicaManager,
        ReplicaSpec,
        Router,
        make_router_http_server,
    )

    prompt = [9, 10, 11, 12, 13, 14, 15, 16, 17, 5]
    mono = _tiny_service("both")
    try:
        code, body, _ = _post(
            mono[2] + "/generate",
            {"prompt": prompt, "max_new_tokens": 4},
        )
        assert code == 200, body
        r_mono = json.loads(body)
        _TINY_FNS["decode"].update(mono[0].engine._fns)
    finally:
        mono[1].shutdown()
        mono[1].server_close()
        mono[0].close()

    daemons = []

    def launcher_for(phase):
        def spawn(name, port):
            svc, httpd, base, pool = _tiny_service(
                phase, **({"kv_layout": "dense"}
                          if phase == "prefill" else {}),
            )
            daemons.append((svc, httpd, pool))
            return SimpleNamespace(url=base, stop=lambda: None)
        return CallableLauncher(spawn)

    managers = [
        ReplicaManager(
            launcher_for(phase),
            ReplicaSpec(target=1, set_name=phase, phase=phase,
                        health_poll_s=0.2, health_timeout_s=5.0),
        )
        for phase in ("prefill", "decode")
    ]
    router = Router(manager=managers, health_poll_s=0.2,
                    health_timeout_s=5.0)
    rhttpd = None
    try:
        for m in managers:
            m.tick()
        router.poll_once()
        assert router.phase_split_active(), router.status()
        rhttpd = make_router_http_server(router, "127.0.0.1", 0)
        threading.Thread(
            target=rhttpd.serve_forever, daemon=True
        ).start()
        rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        for i in range(3):
            code, body, hdrs = _post(
                rbase + "/generate",
                {"prompt": prompt, "max_new_tokens": 4},
            )
            assert code == 200, body
            assert json.loads(body)["ids"] == r_mono["ids"]
            assert hdrs["x-mlcomp-replica"].startswith("decode")

        st = router.status()
        assert st["phase_split"] is True
        assert st["live_by_phase"] == {
            "both": 0, "prefill": 1, "decode": 1,
        }
        assert st["counts"]["handoffs"] == 3
        assert st["counts"]["handoff_bytes"] > 0
        assert st["counts"]["handoff_failures"] == 0
        # keep-alive reuse: 3 two-hop requests over 2 upstreams dialed
        # at most a couple of sockets, the rest were parked reuses
        assert st["conn_pool"]["reuses"] >= 2, st["conn_pool"]

        # decode-side quiesce: nothing leaked on the import path
        dec_svc = next(
            s for s, _h, _p in daemons if s.phase == "decode"
        )
        eng = dec_svc.engine
        assert eng.stats()["handoffs_imported"] == 3
        # quiesce on the POOL's own state: the response resolves a
        # beat before the loop thread releases the slot's pages
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            pst = eng._pool.stats()
            if pst["pages_used"] == pst["pages_reclaimable"]:
                break
            time.sleep(0.05)
        assert pst["outstanding_page_leases"] == 0, pst
        # every still-used page is registry-held (reclaimable), i.e.
        # no slot or lease leaked a page past quiesce
        assert pst["pages_used"] == pst["pages_reclaimable"], pst
        assert pst["pages_free"] + pst["pages_used"] == (
            pst["pages_total"]
        ), pst
    finally:
        if rhttpd is not None:
            rhttpd.shutdown()
            rhttpd.server_close()
        router.close()
        for m in managers:
            m.close(stop_replicas=True)
        for svc, httpd, pool in daemons:
            pool.update(svc.engine._fns)
            httpd.shutdown()
            httpd.server_close()
            svc.close()

import pytest

from mlcomp_tpu.utils.registry import Registry, RegistryError


def test_register_and_get_case_insensitive():
    r = Registry("things")

    @r.register("My-Thing")
    class Thing:
        pass

    assert r.get("my_thing") is Thing
    assert "MY-THING" in r
    assert len(r) == 1


def test_duplicate_raises():
    r = Registry("things")
    r.register("a", obj=object())
    with pytest.raises(RegistryError):
        r.register("a", obj=object())


def test_same_object_reregister_ok():
    r = Registry("things")
    o = object()
    r.register("a", obj=o)
    r.register("a", obj=o)  # idempotent
    assert len(r) == 1


def test_unknown_lists_known():
    r = Registry("things")
    r.register("alpha", obj=object())
    with pytest.raises(RegistryError, match="alpha"):
        r.get("beta")


def test_create():
    r = Registry("things")

    @r.register("pair")
    class Pair:
        def __init__(self, x, y=0):
            self.x, self.y = x, y

    p = r.create("pair", 1, y=2)
    assert (p.x, p.y) == (1, 2)

"""Metrics-history ring (mlcomp_tpu/obs/history.py): ring eviction,
window queries, counter-reset clamping, quantile materialization, and
sampler-thread shutdown — all against an injected clock, no jax."""

import time

import pytest

from mlcomp_tpu.obs.history import MetricsHistory, bucket_quantile
from mlcomp_tpu.obs.metrics import Registry


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_history(reg=None, **kw):
    clock = kw.pop("clock", Clock())
    kw.setdefault("interval_s", 5.0)
    kw.setdefault("start", False)
    return MetricsHistory(reg or Registry(), clock=clock, **kw), clock


def test_ring_evicts_oldest_at_max_samples():
    hist, clock = make_history(max_samples=3)
    c = hist.registry.counter("t_requests_total", "")
    for i in range(5):
        c.inc()
        clock.t += 5
        hist.sample_now()
    entries = hist.entries()
    assert len(entries) == 3  # bounded
    # the survivors are the NEWEST three (totals 3, 4, 5)
    assert [e["counters"]["t_requests_total"] for e in entries] == [
        3.0, 4.0, 5.0
    ]
    assert hist.stats()["samples_taken"] == 5
    assert hist.stats()["samples_held"] == 3


def test_window_query_trims_to_trailing_window():
    hist, clock = make_history()
    g = hist.registry.gauge("t_depth", "")
    for i in range(6):
        g.set(i)
        clock.t += 10
        hist.sample_now()
    assert len(hist.entries()) == 6
    # the last 25 s hold the samples taken at t-20, t-10, t-0
    recent = hist.entries(window_s=25)
    assert [e["gauges"]["t_depth"] for e in recent] == [3.0, 4.0, 5.0]
    q = hist.query(window_s=25)
    assert len(q["samples"]) == 3
    assert q["samples"][-1]["age_s"] == 0.0
    assert q["window_s"] == 25


def test_counter_deltas_and_reset_clamp():
    hist, clock = make_history()
    c = hist.registry.counter("t_tokens_total", "")
    c.inc(10)
    hist.sample_now()
    c.inc(7)
    clock.t += 5
    hist.sample_now()
    deltas = [e["counter_deltas"]["t_tokens_total"] for e in hist.entries()]
    # first sample has no predecessor: its whole total is the delta
    assert deltas == [10.0, 7.0]
    # simulate a restart: the counter steps BACKWARDS (a fresh process
    # re-registered at a lower total).  The delta must clamp to the new
    # value — rate() semantics — never go negative.
    c._values[()] = 3.0
    clock.t += 5
    hist.sample_now()
    assert hist.entries()[-1]["counter_deltas"]["t_tokens_total"] == 3.0
    assert hist.window_delta("t_tokens_total") == 20.0


def test_labeled_counters_keyed_like_the_exposition():
    hist, clock = make_history()
    c = hist.registry.counter("t_rej_total", "", labelnames=("reason",))
    c.inc(2, reason="queue_full")
    c.inc(1, reason="concurrency")
    hist.sample_now()
    e = hist.entries()[-1]
    assert e["counters"]['t_rej_total{reason="queue_full"}'] == 2.0
    assert e["counters"]['t_rej_total{reason="concurrency"}'] == 1.0


def test_histogram_interval_quantiles_materialized():
    hist, clock = make_history()
    h = hist.registry.histogram(
        "t_lat_ms", "", buckets=(10.0, 100.0, 1000.0)
    )
    for v in (5, 50, 50, 500):
        h.observe(v)
    hist.sample_now()
    qs = hist.entries()[-1]["quantiles"]["t_lat_ms"]
    # rank math over buckets [10, 100, 1000] with counts [1, 2, 1]:
    # p50's rank 2 lands in the (10, 100] bucket
    assert 10.0 < qs["p50"] <= 100.0
    assert 100.0 < qs["p99"] <= 1000.0
    # the NEXT interval has no observations -> quantiles are None, and
    # the windowed aggregate still answers from the first interval
    clock.t += 5
    hist.sample_now()
    assert hist.entries()[-1]["quantiles"]["t_lat_ms"]["p50"] is None
    assert hist.window_quantile("t_lat_ms", 0.5) == qs["p50"]


def test_quantile_mass_above_largest_bucket():
    # observations past the last finite bound live only in the implicit
    # +Inf count; the quantile must account for that mass and answer
    # the largest finite bound for ranks inside it
    assert bucket_quantile([10.0, 100.0], [0, 1], 0.99, total=10) == 100.0
    assert bucket_quantile([10.0, 100.0], [0, 0], 0.5, total=0) is None


def test_histogram_reset_clamp():
    hist, clock = make_history()
    h = hist.registry.histogram("t_lat_ms", "", buckets=(10.0, 100.0))
    h.observe(5)
    h.observe(5)
    hist.sample_now()
    # restart: fewer lifetime observations than the last sample saw
    h._values[()] = [[1, 0], 5.0, 1]
    clock.t += 5
    hist.sample_now()
    e = hist.entries()[-1]["hist"]["t_lat_ms"]
    assert e["delta_n"] == 1 and e["delta_counts"] == [1, 0]


def test_bad_construction_rejected():
    with pytest.raises(ValueError):
        MetricsHistory(Registry(), interval_s=0, start=False)
    with pytest.raises(ValueError):
        MetricsHistory(Registry(), max_samples=1, start=False)


def test_callbacks_fire_and_errors_are_contained():
    hist, clock = make_history()
    seen = []
    hist.add_callback(lambda: seen.append(1))
    hist.add_callback(lambda: 1 / 0)
    hist.sample_now()
    hist.sample_now()
    assert seen == [1, 1]
    assert hist.stats()["callback_errors"] == 2


def test_sampler_thread_samples_and_shuts_down():
    reg = Registry()
    reg.counter("t_total", "").inc()
    hist = MetricsHistory(reg, interval_s=0.02, start=True)
    deadline = time.time() + 5.0
    while hist.stats()["samples_taken"] < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert hist.stats()["samples_taken"] >= 2
    hist.close()
    assert not hist._thread.is_alive()
    taken = hist.stats()["samples_taken"]
    time.sleep(0.06)
    assert hist.stats()["samples_taken"] == taken  # really stopped


def test_own_metrics_registered():
    reg = Registry()
    hist, clock = make_history(reg=reg)
    hist.sample_now()
    text = reg.render()
    assert "mlcomp_metrics_history_samples_total 1" in text
    assert "mlcomp_metrics_history_span_seconds" in text


def test_close_unregisters_the_collector():
    # regression: a registry can outlive its sampler (bench's obs_spine
    # A/B churns them against one engine registry) — close() must
    # deregister, or dead collectors accumulate and keep republishing
    # frozen values
    reg = Registry()
    before = len(reg._collectors)
    hist, _ = make_history(reg=reg)
    assert len(reg._collectors) == before + 1
    hist.close()
    assert len(reg._collectors) == before
    reg.render()  # and rendering after close is collector-free/clean

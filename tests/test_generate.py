"""KV-cache generation vs full-forward decoding, sampling, ragged prompts."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import (
    generate,
    init_cache,
    process_logits,
    sample_token,
)


@pytest.fixture(scope="module")
def lm():
    model = create_model(
        {
            "name": "transformer_lm",
            "vocab_size": 64,
            "hidden": 32,
            "layers": 2,
            "heads": 4,
            "kv_heads": 2,
            "mlp_dim": 64,
            "dtype": "float32",
        }
    )
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(1, 64, size=(2, 5)), jnp.int32
    )
    variables = model.init(jax.random.PRNGKey(0), prompt)
    return model, {"params": variables["params"]}, prompt


def _greedy_no_cache(model, variables, prompt, n):
    """Reference decode: full forward over the growing sequence each step."""
    ids = prompt
    for _ in range(n):
        logits = model.apply(variables, ids)
        ids = jnp.concatenate(
            [ids, jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)], axis=1
        )
    return ids


def test_greedy_cache_matches_full_forward(lm):
    model, variables, prompt = lm
    out = jax.jit(partial(generate, model, max_new_tokens=6))(
        variables, prompt=prompt
    )
    ref = _greedy_no_cache(model, variables, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_left_padded_prompts_match_unpadded(lm):
    model, variables, _ = lm
    rs = np.random.RandomState(1)
    short = jnp.asarray(rs.randint(1, 64, size=(1, 3)), jnp.int32)
    long = jnp.asarray(rs.randint(1, 64, size=(1, 5)), jnp.int32)
    # batch them left-padded to a common length of 5
    padded = jnp.concatenate([jnp.zeros((1, 2), jnp.int32), short], axis=1)
    batch = jnp.concatenate([padded, long], axis=0)
    mask = jnp.asarray([[0, 0, 1, 1, 1], [1, 1, 1, 1, 1]], jnp.bool_)

    out = generate(model, variables, batch, 4, prompt_mask=mask)
    ref_short = generate(model, variables, short, 4)
    ref_long = generate(model, variables, long, 4)
    np.testing.assert_array_equal(np.asarray(out[0, 5:]), np.asarray(ref_short[0, 3:]))
    np.testing.assert_array_equal(np.asarray(out[1, 5:]), np.asarray(ref_long[0, 5:]))


def test_eos_forces_padding(lm):
    model, variables, prompt = lm
    first = int(np.asarray(generate(model, variables, prompt, 1))[0, -1])
    out = np.asarray(
        generate(model, variables, prompt, 5, eos_id=first, pad_id=63)
    )
    row = out[0, prompt.shape[1]:]
    assert row[0] == first
    np.testing.assert_array_equal(row[1:], np.full(4, 63))


def test_sampling_deterministic_per_key(lm):
    model, variables, prompt = lm
    gen = partial(
        generate, model, variables, prompt, 8,
        temperature=0.8, top_k=20, top_p=0.95,
    )
    a = np.asarray(gen(rng=jax.random.PRNGKey(7)))
    b = np.asarray(gen(rng=jax.random.PRNGKey(7)))
    c = np.asarray(gen(rng=jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, prompt.shape[1] + 8)
    assert not np.array_equal(a, c)  # different key, different draw


def test_process_logits_top_k_top_p():
    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.05, 0.05]]))
    top2 = process_logits(logits, 1.0, 2, None)
    assert np.isfinite(np.asarray(top2)[0, :2]).all()
    assert np.isneginf(np.asarray(top2)[0, 2:]).all()
    # top_p=0.65: {0.4, 0.3} reach 0.7 >= 0.65 with the exclusive-prefix
    # rule keeping both; 0.2 and below are cut
    topp = process_logits(logits, 1.0, None, 0.65)
    assert np.isfinite(np.asarray(topp)[0, :2]).all()
    assert np.isneginf(np.asarray(topp)[0, 2:]).all()
    # greedy winner survives any filtering
    assert int(jnp.argmax(top2)) == 0


def test_process_logits_rejects_degenerate_knobs():
    logits = jnp.zeros((1, 8))
    with pytest.raises(ValueError):
        process_logits(logits, 1.0, 0, None)
    with pytest.raises(ValueError):
        process_logits(logits, 1.0, None, 0.0)
    # over-large top_k clamps to vocab instead of crashing lax.top_k
    out = process_logits(logits, 1.0, 100, None)
    assert np.isfinite(np.asarray(out)).all()


def test_sample_token_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 2.0, 0.3], [5.0, 0.0, -1.0]])
    tok = sample_token(jax.random.PRNGKey(0), logits, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(tok), [1, 0])


@pytest.mark.parametrize("seed", [3, 7])
def test_moe_lm_generates_with_cache(seed):
    """MoE decoder: cache decode == full-forward greedy decoding.

    Seed 7 historically made both batch rows route to the same expert in
    a decode step — under capacity routing the second row's expert output
    was dropped and generation diverged from the full forward.  Inference
    routing is now dense (drop-free), so equality must hold for ANY seed.
    """
    model = create_model(
        {
            "name": "moe_lm",
            "vocab_size": 64,
            "hidden": 32,
            "layers": 2,
            "heads": 4,
            "n_experts": 4,
            "d_ff": 64,
            "moe_every": 2,
            "dtype": "float32",
        }
    )
    prompt = jnp.asarray(
        np.random.RandomState(seed).randint(1, 64, size=(2, 5)), jnp.int32
    )
    variables = {
        "params": model.init(jax.random.PRNGKey(seed), prompt)["params"]
    }
    out = generate(model, variables, prompt, 5)
    ref = _greedy_no_cache(model, variables, prompt, 5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_chunked_prefill_matches_full(lm):
    """Two decode calls with s>1 (chunked prefill) == one full forward;
    exercises the i>0 branch of the prefill cond."""
    model, variables, _ = lm
    ids = jnp.asarray(np.random.RandomState(4).randint(1, 64, (2, 8)), jnp.int32)
    from mlcomp_tpu.models.generation import init_cache

    cache = init_cache(model, 2, 8)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[None], (2, 8))
    logits_a, upd = model.apply(
        {**variables, "cache": cache}, ids[:, :5], decode=True,
        positions=pos[:, :5], mutable=["cache"],
    )
    logits_b, _ = model.apply(
        {**variables, "cache": upd["cache"]}, ids[:, 5:], decode=True,
        positions=pos[:, 5:], mutable=["cache"],
    )
    chunked = jnp.concatenate([logits_a, logits_b], axis=1)
    full = model.apply(variables, ids)
    np.testing.assert_allclose(
        np.asarray(chunked), np.asarray(full), atol=1e-4, rtol=1e-4
    )


def test_generate_executor_writes_ids(tmp_path):
    from mlcomp_tpu.executors import load_all
    from mlcomp_tpu.executors.base import ExecutionContext, create_executor

    load_all()
    out = tmp_path / "gen.npz"
    ex = create_executor(
        "generate",
        {
            "out": str(out),
            "max_new_tokens": 4,
            "model": {
                "name": "transformer_lm",
                "vocab_size": 32,
                "hidden": 16,
                "layers": 1,
                "heads": 2,
                "dtype": "float32",
            },
            "data": {
                "infer": {
                    "name": "synthetic_tokens",
                    "n": 6,
                    "seq_len": 8,
                    "vocab_size": 32,
                    "batch_size": 8,
                }
            },
        }
    )
    res = ex.work(
        ExecutionContext(
            dag_id=1, task_id=1, task_name="gen", args=ex.args,
            workdir=str(tmp_path),
        )
    )
    ids = np.load(out)["ids"]
    # 8 prompt + 4 generated; the loader pads the 6-row tail to batch_size 8
    # and the executor drops the pad rows via the batch's 'valid' mask
    assert ids.shape == (6, 12)
    assert res["n"] == 6


def test_generate_executor_masks_left_padding(tmp_path):
    """The executor derives prompt_mask from pad_id (left-pad contract):
    a padded npz prompt set decodes identically to its unpadded rows."""
    from mlcomp_tpu.executors.base import ExecutionContext
    from mlcomp_tpu.executors.infer import GenerateExecutor

    model = {
        "name": "transformer_lm", "vocab_size": 32, "hidden": 16,
        "layers": 1, "heads": 2, "dtype": "float32",
    }
    rs = np.random.RandomState(7)
    rows = rs.randint(1, 32, size=(8, 6)).astype(np.int32)
    padded = np.concatenate([np.zeros((8, 3), np.int32), rows], axis=1)

    def run(arr, name):
        p = tmp_path / f"{name}.npz"
        np.savez(p, x=arr)
        out = tmp_path / f"{name}_out.npz"
        ex = GenerateExecutor(
            out=str(out), max_new_tokens=4, model=model,
            data={"infer": {"name": "npz", "path": str(p), "batch_size": 8}},
        )
        ex.work(ExecutionContext(
            dag_id=1, task_id=1, task_name=name, args=ex.args,
            workdir=str(tmp_path),
        ))
        return np.load(out)["ids"]

    got = run(padded, "padded")
    ref = run(rows, "plain")
    # both runs init fresh params from the same seed; greedy decode of the
    # padded batch must continue each row exactly like its unpadded twin
    np.testing.assert_array_equal(got[:, 9:], ref[:, 6:])


def test_init_cache_rejects_non_decode_model():
    model = create_model({"name": "mlp", "num_classes": 4, "hidden": [8]})
    with pytest.raises((ValueError, TypeError)):
        init_cache(model, 2, 8)


def test_repetition_penalty_rowwise():
    """rp=1.0 is bit-neutral vs the plain rowwise path; an extreme
    penalty never re-emits a seen token (prompt or generated)."""
    m = create_model({"name": "transformer_lm", "vocab_size": 64,
                      "hidden": 32, "layers": 1, "heads": 2})
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 16), jnp.int32))
    prompt = jnp.asarray([[3, 4, 5], [6, 7, 8]], jnp.int32)
    t0 = jnp.zeros((2,))
    base = generate(m, v, prompt, 6, temperature=t0,
                    rng=jax.random.PRNGKey(1))
    neutral = generate(m, v, prompt, 6, temperature=t0,
                       repetition_penalty=jnp.ones((2,)),
                       rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(base), np.asarray(neutral))

    hard = generate(m, v, prompt, 6, temperature=t0,
                    repetition_penalty=jnp.full((2,), 10.0) ** 5,
                    rng=jax.random.PRNGKey(1))
    for r in range(2):
        seen = set(np.asarray(prompt[r]).tolist())
        for tok in np.asarray(hard[r, 3:]).tolist():
            assert tok not in seen, f"re-emitted {tok}"
            seen.add(tok)

    # static path refuses the knob (it needs the rowwise machinery)
    with pytest.raises(ValueError, match="rowwise"):
        generate(m, v, prompt, 4, temperature=0.0,
                 repetition_penalty=jnp.ones((2,)))

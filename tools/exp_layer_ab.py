"""A/B one decoder layer's worth of decode GEMVs: round-3 512x512 blocks
vs the new _auto_blocks heuristic.  Marginal fori_loop timing, one
process, interleaved, median of 7."""
import statistics
import time

import jax
import jax.numpy as jnp

from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
from mlcomp_tpu.ops.quant import quantize_leaf

B, D, M = 8, 2048, 8192
key = jax.random.PRNGKey(0)


def qw(d_in, d_out, k):
    w = jax.random.normal(jax.random.fold_in(key, k), (d_in, d_out), jnp.float32)
    leaf = quantize_leaf(w)
    return leaf["q8"], leaf["q8_scale"].reshape(-1)


wq, wk, wv, wo = (qw(D, D, i) for i in range(4))
wg, wu = qw(D, M, 4), qw(D, M, 5)
wd = qw(M, D, 6)
LAYER_BYTES = 4 * D * D + 3 * D * M


def layer(x, bn, bd):
    def qm(h, w):
        return quant_matmul(h, w[0], w[1], block_n=bn, block_d=bd)

    a = qm(x, wq) + qm(x, wk) + qm(x, wv)
    x = x + qm(a * 1e-2, wo)
    g, u = qm(x, wg), qm(x, wu)
    return x + qm(jax.nn.silu(g) * u, wd) * 1e-2


wqkv = (
    jnp.concatenate([wq[0], wk[0], wv[0]], axis=1),
    jnp.concatenate([wq[1], wk[1], wv[1]]),
)
wgu = (
    jnp.concatenate([wg[0], wu[0]], axis=1),
    jnp.concatenate([wg[1], wu[1]]),
)


def layer_fused(x):
    qkv = quant_matmul(x, wqkv[0], wqkv[1])
    a = qkv[:, :D] + qkv[:, D:2 * D] + qkv[:, 2 * D:]
    x = x + quant_matmul(a * 1e-2, wo[0], wo[1])
    gu = quant_matmul(x, wgu[0], wgu[1])
    h = jax.nn.silu(gu[:, :M]) * gu[:, M:]
    return x + quant_matmul(h, wd[0], wd[1]) * 1e-2


VARIANTS = {
    "old_512x512": lambda x: layer(x, 512, 512),
    "auto": lambda x: layer(x, None, None),
    "auto_fused": layer_fused,
}
N_LO, N_HI = 64, 1024


def looped(f, n):
    return jax.jit(
        lambda x: jax.lax.fori_loop(0, n, lambda i, h: f(h) * 1e-1, x)
    )


x0 = jax.random.normal(jax.random.fold_in(key, 99), (B, D), jnp.bfloat16)
fns = {(nm, n): looped(f, n) for nm, f in VARIANTS.items() for n in (N_LO, N_HI)}
print("compiling...", flush=True)
for kk, fn in fns.items():
    t0 = time.perf_counter()
    float(fn(x0)[0, 0])
    print(f"  {kk}: {time.perf_counter()-t0:.1f}s", flush=True)

times = {k: [] for k in fns}
for w in range(7):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        float(fn(x0)[0, 0])
        times[kk].append(time.perf_counter() - t0)

roof = LAYER_BYTES / 819e9 * 1e6
print(f"\nroofline {roof:.2f} us/layer")
for nm in VARIANTS:
    t_lo = statistics.median(times[(nm, N_LO)])
    t_hi = statistics.median(times[(nm, N_HI)])
    per = (t_hi - t_lo) / (N_HI - N_LO) * 1e6
    print(f"{nm:12s}: {per:8.2f} us/layer  ({roof/per*100:5.1f}% of roofline)")

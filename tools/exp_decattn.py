"""decode_attention block sweep at bench shapes (B=8, Hkv=16, dh=128,
l_buf=2304): blk=256 (today's largest divisor of 2304) runs 9 grid
steps/call; blk=768 runs 3.  Marginal fori_loop timing, one process."""
import statistics
import time

import jax
import jax.numpy as jnp

from mlcomp_tpu.ops.pallas.decode_attention import decode_attention

B, HKV, DH, LBUF = 8, 16, 128, 2304
key = jax.random.PRNGKey(0)
k8 = jax.random.randint(key, (B, HKV, LBUF, DH), -127, 127, jnp.int8)
v8 = jax.random.randint(jax.random.fold_in(key, 1), (B, HKV, LBUF, DH), -127, 127, jnp.int8)
ks = jax.random.uniform(jax.random.fold_in(key, 2), (B, HKV, 1, LBUF), jnp.float32) * 0.01
vs = jax.random.uniform(jax.random.fold_in(key, 3), (B, HKV, 1, LBUF), jnp.float32) * 0.01
start = jnp.zeros((B,), jnp.int32)
stop = jnp.full((B,), 2200, jnp.int32)

CASES = {"blk256": 256, "blk768": 768, "blk1152": 1152}
N_LO, N_HI = 64, 512


def looped(blk, n):
    def body(i, q):
        o = decode_attention(q, k8, ks, v8, vs, kv_start=start,
                             kv_stop=stop, block_kv=blk)
        return (o * 1e-3 + q * 0.5).astype(q.dtype)

    return jax.jit(lambda q: jax.lax.fori_loop(0, n, body, q))


q0 = jax.random.normal(jax.random.fold_in(key, 9), (B, HKV, DH), jnp.bfloat16)
fns = {}
for nm, blk in CASES.items():
    for n in (N_LO, N_HI):
        fns[(nm, n)] = looped(blk, n)
for kk, fn in fns.items():
    t0 = time.perf_counter()
    float(fn(q0)[0, 0, 0])
    print(f"  {kk}: {time.perf_counter()-t0:.1f}s", flush=True)

times = {k: [] for k in fns}
for _ in range(7):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        float(fn(q0)[0, 0, 0])
        times[kk].append(time.perf_counter() - t0)

roof = 2 * B * HKV * 2200 * DH / 819e9 * 1e6  # live-window K+V int8 bytes
print(f"\nlive-window roofline {roof:.1f} us/call")
for nm in CASES:
    t_lo = statistics.median(times[(nm, N_LO)])
    t_hi = statistics.median(times[(nm, N_HI)])
    per = (t_hi - t_lo) / (N_HI - N_LO) * 1e6
    print(f"{nm:8s}: {per:8.2f} us/call ({roof/per*100:5.1f}% of live roofline)")

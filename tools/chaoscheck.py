#!/usr/bin/env python
"""Chaos harness for the serving resilience layer: a live toy daemon is
driven through each serving fault point (utils/faults.py) and must come
back healthy with nothing leaked.

Scenarios, against the real serve daemon HTTP stack
(``serve.make_http_server``, continuous engine, prefix cache on,
watchdog armed):

- **slow resolve** (``engine.resolve`` sleep): latency rises, nothing
  breaks — tokens stay bit-identical;
- **dispatch exception** (``engine.dispatch`` raise): the drive loop
  fails every waiter with the error and dies CLEANLY; the watchdog
  restarts it and the replayed baseline traffic is bit-identical;
- **dispatch stall** (``engine.dispatch`` sleep past
  ``dispatch_stall_timeout``): the watchdog fails the in-flight
  request in bounded time (far before its deadline), ``/healthz``
  serves 503 while wedged, and once the runtime unsticks the loop dies
  and is restarted — replay bit-identical;
- **cache lookup raise** (``cache.lookup``): contained to a
  degraded-mode cache BYPASS — the request still succeeds with exact
  tokens, ``cache_hit_tokens`` 0, ``cache_degraded`` counted;
- **cache capture raise** (``cache.capture``): contained to
  ``insert_errors`` on the capture worker; serving continues.
- **fused-prefill raise** (``engine.fused_prefill``): a fault while an
  admission chunk is being fused into the decode dispatch fails ONLY
  the admitting request — the streaming survivor's tokens stay
  bit-identical (its boundary falls back to a plain decode dispatch),
  nothing leaks, and the next admission succeeds;
- **page-pool exhaustion** (paged KV): a concurrent flood past the
  free-page budget produces BOUNDED 429s with reason
  ``no_free_pages`` (never a hang, never a 5xx), survivors stay
  bit-identical, and at quiesce the pool holds zero leaked pages;
- **lazy-allocation exhaustion MID-DECODE** (scenario 7, fused paged
  attention): admission overcommits the pool against decode budgets
  (pages allocate lazily as cursors cross page boundaries), so a
  tightly-sized pool can run dry at a crossing with rows mid-stream.
  The starved row must fail TYPED (``NoFreePages``, status
  ``no_free_pages``) at the dispatch boundary — never a hang, never a
  fleet error — its freed pages must unblock the neighbour starved in
  the same tick, the surviving stream's tokens must be bit-identical
  to a solo run, and at quiesce the pool holds zero leaked pages;
- **adaptive-K switch mid-stream** (scenario 9, adaptive dispatch
  depth — the serve default): a concurrent burst pushes the ladder
  controller up (and the quiesce snap brings it back down) while a
  fault-stretched stream decodes — the survivor's tokens must be
  bit-identical to a solo run (the K-invariant RNG/scan contract),
  the controller must have actually switched, and the fleet drains
  clean;
- **prefill replica killed mid-transfer** (scenario 10, disaggregated
  serving): a phase-split fleet's prefill replica dies halfway
  through writing a KV-page handoff blob.  The router must detect the
  short read, mark the victim down, and complete the request
  bit-identically through the surviving prefill replica; a partial
  blob that REACHES a decode replica must be rejected TYPED (400
  ``bad_handoff``) with zero pages/leases touched; and at quiesce
  both sides hold zero leaked pages, leases, or slots.

The daemon runs the PAGED device KV layout (``kv_layout="paged"``,
mlcomp_tpu/kvpool), so every scenario above also exercises the page
pool's recovery contract — in particular the watchdog-restart
scenarios prove ``pool.reset()`` rebuilds a clean allocator alongside
the fresh device carry.

Recovery invariants asserted after EVERY scenario:

- no future hangs: every HTTP call returns (success or a typed error)
  well inside its deadline;
- no slot leaks: ``active_slots`` and ``queue_depth`` drain to 0;
- no pin leaks: the prefix index reports 0 ``outstanding_leases`` and
  0 ``pinned_nodes`` (capture queue flushed);
- health recovers: ``/healthz`` is 200/ok again, and surviving
  requests' token streams are bit-identical to the fault-free run.

No TPU needed (CPU jax), finishes in seconds; tests/test_chaoscheck.py
wires it into tier-1 like cachecheck/obs_check.  Standalone:

    python tools/chaoscheck.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mlcomp_tpu.utils import faults  # noqa: E402


# share the engines' compiled programs across same-config daemons (the
# tests/test_serve.py _CONT_FNS idiom): the replica-kill fleet scenario
# builds three more default-config daemons, and each would otherwise
# re-pay the full prefill/insert/dispatch compile bill — the dominant
# line in this harness's wall time.  Only the exact default svc_kw
# shares; scenario 6's tight pool (different page-table shapes) opts
# out by construction.
_SHARED_FNS: dict = {}
_SHARED_KW = {"kv_layout": "paged", "max_slots": 4, "kv_pages": 34}


class _Daemon:
    """The toy serving daemon + typed HTTP helpers."""

    def __init__(self, **svc_kw):
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np

        from mlcomp_tpu.models import create_model
        from mlcomp_tpu.serve import GenerationService, make_http_server
        from mlcomp_tpu.train.state import init_model

        model = create_model({
            "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
            "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
        })
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(1, 64, (1, 8))
        )
        params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
        # generous stall timeout at construction (the first dispatches
        # COMPILE, and compile time is busy time to the watchdog); the
        # stall scenario tightens it once the programs are warm
        svc_kw.setdefault("kv_layout", "paged")
        svc_kw.setdefault("max_slots", 4)
        # roomy page pool: scenarios 0-5 test FAULT containment, and a
        # pool sized to the dense-equal default (8 allocatable pages at
        # this geometry) starves them into no_free_pages 429s once two
        # 10-token streams and the registry's pins coexist — capacity
        # limits get their own tightly-sized daemon in scenario 6
        svc_kw.setdefault("kv_pages", 34)
        self.svc = GenerationService(
            model, {"params": params}, batch_sizes=(1, 2),
            prompt_buckets=(16,), max_new_buckets=(8,),
            prefix_cache=True, prefill_chunk=8,
            dispatch_stall_timeout=60.0,
            **svc_kw,
        )
        self._pool_fns = svc_kw == _SHARED_KW and (
            self.svc.engine is not None
        )
        if self._pool_fns:
            self.svc.engine._fns.update(_SHARED_FNS)
        self.httpd = make_http_server(self.svc, "127.0.0.1", 0, "chaos")
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.base = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def generate(self, ids, deadline_s=None, timeout=120):
        """POST /generate -> (http_code, payload dict).  Never raises
        on HTTP error codes — the codes ARE the contract under test."""
        body = {"prompt": list(ids), "max_new_tokens": 4}
        if deadline_s is not None:
            body["deadline_s"] = deadline_s
        req = urllib.request.Request(
            f"{self.base}/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def open_stream(self, ids, n_new=8, timeout=120):
        """POST /generate with "stream": true; returns the open SSE
        response (headers are sent before the first token, so the row
        keeps decoding while the caller does other work)."""
        body = {"prompt": list(ids), "max_new_tokens": n_new,
                "stream": True}
        req = urllib.request.Request(
            f"{self.base}/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        return urllib.request.urlopen(req, timeout=timeout)

    @staticmethod
    def read_stream(resp):
        """Drain an SSE response -> (token list, final result dict);
        raises on an error event (the stream under test must survive)."""
        toks, final = [], None
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            item = json.loads(line[len("data: "):])
            if "error" in item:
                raise AssertionError(f"stream errored: {item}")
            if item.get("done"):
                final = item
                break
            toks.append(item["token"])
        resp.close()
        return toks, final

    def healthz(self):
        try:
            with urllib.request.urlopen(
                f"{self.base}/healthz", timeout=10
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def wait_healthy(self, deadline_s=15.0) -> float:
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < deadline_s:
            code, h = self.healthz()
            if code == 200 and h.get("ok"):
                return time.perf_counter() - t0
            time.sleep(0.05)
        raise AssertionError(
            f"daemon did not recover within {deadline_s}s: {self.healthz()}"
        )

    def assert_drained(self, what: str) -> None:
        """No leaked slots/queue entries/pins after a scenario."""
        self.svc.prefix_cache.flush()
        eng = self.svc.engine
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            st = eng.stats()
            if st["active_slots"] == 0 and st["queue_depth"] == 0:
                break
            time.sleep(0.05)
        st = eng.stats()
        assert st["active_slots"] == 0, (what, st)
        assert st["queue_depth"] == 0, (what, st)
        cs = self.svc.prefix_cache.stats()
        assert cs["outstanding_leases"] == 0, (what, cs)
        assert cs["pinned_nodes"] == 0, (what, cs)
        assert cs["capture_queue_depth"] == 0, (what, cs)
        self.svc.prefix_cache.index.check_invariants()

    def harvest_fns(self):
        """Bank this daemon's compiled programs for the next
        same-config daemon (restart-heavy scenarios would otherwise
        recompile per incarnation)."""
        if self._pool_fns:
            _SHARED_FNS.update(self.svc.engine._fns)

    def close(self):
        self.harvest_fns()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.svc.close()


def run() -> dict:
    d = _Daemon()
    out = {}
    prompts = [
        [9, 10, 11, 12, 13, 14, 15, 16, 17, p] for p in (1, 2, 3)
    ]

    def drive_baseline(tag):
        got = []
        for p in prompts:
            code, payload = d.generate(p)
            assert code == 200, (tag, code, payload)
            got.append(payload["ids"])
        d.svc.prefix_cache.flush()
        return got

    try:
        baseline = drive_baseline("warmup")
        # replay once: surviving traffic must be deterministic before
        # any fault makes "bit-identical after recovery" meaningful
        assert drive_baseline("replay") == baseline
        d.assert_drained("baseline")

        # ---- scenario 0: slow resolve — degraded latency, exact tokens
        faults.arm("engine.resolve", flavor="sleep", times=4, seconds=0.05)
        assert drive_baseline("slow_resolve") == baseline
        d.assert_drained("slow_resolve")
        out["slow_resolve"] = "exact"

        # ---- scenario 1: dispatch exception -> clean death -> restart
        restarts0 = d.svc.engine.stats()["watchdog_restarts"]
        faults.arm("engine.dispatch", flavor="raise", times=1)
        t0 = time.perf_counter()
        code, payload = d.generate(prompts[0], deadline_s=30)
        elapsed = time.perf_counter() - t0
        assert code == 500 and "FaultInjected" in payload["error"], (
            code, payload,
        )
        assert elapsed < 20, f"future hung {elapsed:.1f}s past the fault"
        d.wait_healthy()
        assert d.svc.engine.stats()["watchdog_restarts"] == restarts0 + 1
        assert drive_baseline("after_dispatch_exception") == baseline
        d.assert_drained("dispatch_exception")
        out["dispatch_exception"] = {
            "failed_in_s": round(elapsed, 2), "recovered": True,
        }

        # ---- scenario 2: wedged dispatch -> watchdog -> 503 -> restart
        eng = d.svc.engine
        eng.dispatch_stall_timeout = 0.8  # programs are warm now
        faults.arm("engine.dispatch", flavor="sleep", times=1, seconds=2.5)
        saw_503 = []

        def poll_health():
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 4 and not saw_503:
                code, _ = d.healthz()
                if code == 503:
                    saw_503.append(time.perf_counter() - t0)
                time.sleep(0.05)

        poller = threading.Thread(target=poll_health)
        poller.start()
        t0 = time.perf_counter()
        code, payload = d.generate(prompts[0], deadline_s=30)
        elapsed = time.perf_counter() - t0
        poller.join()
        assert code == 500 and payload.get("status") == "engine_stalled", (
            code, payload,
        )
        # the watchdog must beat both the 2.5 s wedge and the deadline
        assert elapsed < 2.4, (
            f"stalled future took {elapsed:.2f}s — the watchdog did not "
            "fail it ahead of the wedge"
        )
        assert saw_503, "/healthz never served 503 during the wedge"
        recovery_s = d.wait_healthy()
        eng.dispatch_stall_timeout = 60.0
        assert eng.stats()["watchdog_restarts"] == restarts0 + 2
        assert drive_baseline("after_stall") == baseline
        d.assert_drained("dispatch_stall")
        out["dispatch_stall"] = {
            "failed_in_s": round(elapsed, 2),
            "recovered_in_s": round(recovery_s, 2),
            "saw_503": True,
        }

        # ---- scenario 3: cache lookup raise -> degraded bypass
        deg0 = d.svc.engine.stats()["cache_degraded"]
        faults.arm("cache.lookup", flavor="raise", times=1)
        code, payload = d.generate(prompts[0])
        assert code == 200 and payload["ids"] == baseline[0], (code, payload)
        assert payload["cache_hit_tokens"] == 0, payload
        assert d.svc.engine.stats()["cache_degraded"] == deg0 + 1
        # and the NEXT identical request hits the cache again
        code, payload = d.generate(prompts[0])
        assert code == 200 and payload["ids"] == baseline[0]
        assert payload["cache_hit_tokens"] > 0, payload
        d.assert_drained("cache_lookup")
        out["cache_lookup_raise"] = "bypassed_exact"

        # ---- scenario 4: cache capture raise -> insert_errors, alive
        err0 = d.svc.prefix_cache.stats()["insert_errors"]
        faults.arm("cache.capture", flavor="raise", times=1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            code, payload = d.generate(
                [40, 41, 42, 43, 44, 45, 46, 47, 48, 49]
            )
            assert code == 200 and len(payload["ids"]) == 4, (code, payload)
            d.svc.prefix_cache.flush()
        assert d.svc.prefix_cache.stats()["insert_errors"] == err0 + 1
        assert drive_baseline("after_capture_fault") == baseline
        d.assert_drained("cache_capture")
        out["cache_capture_raise"] = "contained"

        # ---- scenario 5: fused-prefill raise -> only the admission dies
        # A streams (holding a decode row) while B admits: B's chunk
        # rides A's decode dispatch, so the armed fault fails B —
        # never A, whose boundary falls back to a plain dispatch.  The
        # overlap is a race against A finishing; retry a few times and
        # require at least one armed attempt to engage.
        a_cold, _ = d.read_stream(d.open_stream(prompts[0], 8))
        d.svc.prefix_cache.flush()
        fused0 = d.svc.engine.stats()["fused_chunks"]
        engaged = False
        for attempt in range(5):
            faults.arm("engine.fused_prefill", flavor="raise", times=1)
            resp = d.open_stream(prompts[0], 8)
            code, payload = d.generate(prompts[1], timeout=60)
            a_toks, _ = d.read_stream(resp)
            # the survivor is bit-identical whether or not the race won
            assert a_toks == a_cold, (attempt, a_toks, a_cold)
            if code == 500 and "FaultInjected" in payload.get("error", ""):
                engaged = True
                break
            faults.disarm_all()   # race lost: B admitted unfused
            assert code == 200 and payload["ids"] == baseline[1], (
                attempt, code, payload,
            )
        assert engaged, "fused-prefill fault never engaged an admission"
        d.wait_healthy()
        # the fleet keeps FUSING after the contained fault: replay the
        # overlap fault-free until an admission actually rides a decode
        # dispatch, with exact tokens on both sides
        refused = False
        for _ in range(5):
            resp = d.open_stream(prompts[0], 8)
            code, payload = d.generate(prompts[1], timeout=60)
            a_toks, _ = d.read_stream(resp)
            assert a_toks == a_cold, (a_toks, a_cold)
            assert code == 200 and payload["ids"] == baseline[1], (
                code, payload,
            )
            if d.svc.engine.stats()["fused_chunks"] > fused0:
                refused = True
                break
        assert refused, "no fused admission engaged after the fault"
        assert drive_baseline("after_fused_fault") == baseline
        d.assert_drained("fused_prefill")
        out["fused_prefill_raise"] = {
            "attempts": attempt + 1, "survivor_exact": True,
        }

        code, h = d.healthz()
        assert code == 200 and h["ok"], (code, h)
        out["final_health"] = {
            "watchdog": h["engine"]["watchdog"],
            "cache_degraded": h["engine"]["cache_degraded"],
        }
        out["page_pool_exhaustion"] = _scenario_page_exhaustion()
        out["lazy_page_exhaustion"] = _scenario_lazy_page_exhaustion()
        out["replica_kill"] = _scenario_replica_kill()
        out["adaptive_k_switch"] = _scenario_adaptive_k_switch()
        out["prefill_kill_mid_transfer"] = (
            _scenario_prefill_kill_mid_transfer()
        )
        return out
    finally:
        faults.disarm_all()
        d.close()


def _scenario_page_exhaustion() -> dict:
    """Scenario 6 — paged-KV pool exhaustion (its own daemon: the
    shared daemon above runs a deliberately ROOMY pool so the fault
    scenarios never starve; this one is sized tight so the flood
    actually exhausts it).  A flood past the free-page budget must produce
    BOUNDED 429s with reason ``no_free_pages`` (never a hang, never a
    5xx), the accepted survivors' tokens must be bit-identical to an
    unloaded run, and at quiesce the pool holds zero leaked pages."""
    import threading as _threading

    # TIGHT pool (the engine's dense-equal default at this geometry:
    # 8 allocatable pages) so the flood actually exhausts it — the
    # shared daemon's roomy pool would admit everything
    d = _Daemon(kv_layout="paged", max_slots=4, kv_pages=10)
    try:
        probe = [9, 10, 11, 12, 13, 14, 15, 16, 17, 3]
        code, payload = d.generate(probe)
        assert code == 200, (code, payload)
        baseline = payload["ids"]
        d.svc.prefix_cache.flush()

        results = []
        lock = _threading.Lock()

        def one(i):
            code, payload = d.generate(
                [9, 10, 11, 12, 13, 14, 15, 16, 17, (i % 40) + 3],
                timeout=120,
            )
            with lock:
                results.append((code, payload))

        threads = [
            _threading.Thread(target=one, args=(i,)) for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "a flood call hung"
        ok = [p for c, p in results if c == 200]
        rejected = [p for c, p in results if c == 429]
        other = [(c, p) for c, p in results if c not in (200, 429)]
        assert not other, f"non-contract responses: {other}"
        assert len(ok) + len(rejected) == 16
        assert ok, "the flood starved every request"
        for p in rejected:
            assert p.get("reason") == "no_free_pages", p
            assert p.get("retry_after_s", 0) >= 1.0, p
        # survivors bit-identical: same placement + same prompt shape
        # as the probe — greedy decode under the paged layout must not
        # be perturbed by neighbours, rejects, or elastic scaling
        code, payload = d.generate(probe)
        assert code == 200 and payload["ids"] == baseline, (code, payload)
        d.assert_drained("page_exhaustion")
        eng = d.svc.engine
        pool = eng._pool
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            pool.reclaim_all()  # registry pins are cache, not leaks
            if pool.alloc.free_pages == pool.alloc.total_pages:
                break
            time.sleep(0.05)
        st = pool.stats()
        assert st["pages_free"] == st["pages_total"], st
        assert st["outstanding_page_leases"] == 0, st
        pool.check_invariants()
        code, h = d.healthz()
        assert code == 200 and h["ok"], (code, h)
        return {
            "accepted": len(ok), "rejected_429": len(rejected),
            "survivors_exact": True, "pages_leaked": 0,
        }
    finally:
        d.close()


def _scenario_lazy_page_exhaustion() -> dict:
    """Scenario 7 — page exhaustion hit MID-DECODE by lazy allocation
    (fused paged attention).  A parked-loop engine makes it
    deterministic: the pool is sized so two streams' INITIAL needs fit
    exactly (admission overcommits against their decode budgets), both
    decode until their cursors approach the lazily-deferred last page,
    and the extend tick finds the pool dry — slot 0 must fail typed
    and free its pages, slot 1 must pick those pages up IN THE SAME
    TICK and finish with tokens bit-identical to its solo run.  Zero
    leaks at quiesce."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401
    import numpy as np
    from concurrent.futures import Future

    from mlcomp_tpu.engine import DecodeEngine, _POISON
    from mlcomp_tpu.kvpool import NoFreePages
    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    # geometry: bucket 16 + max_new 8 + scratch = 25-slot rows over
    # 8-token pages -> 4 pages worst, 3 at admission (prefill + one
    # K=4 dispatch of lookahead).  6 allocatable pages fit BOTH
    # initial needs and NEITHER worst case — the overcommit under test
    eng = DecodeEngine(
        model, {"params": params}, slots=2, prompt_buckets=(16,),
        max_new_cap=8, prefill_chunk=8, steps_per_dispatch=4,
        kv_layout="paged", max_slots=2, kv_pages=2 + 6,
    )
    eng._stop.set()
    eng._queue.put(_POISON)
    eng._thread.join(timeout=60)

    def req(ids, n_new=8):
        return {
            "ids": list(ids), "n_new": n_new, "future": Future(),
            "temperature": 0.0, "top_k": eng.vocab, "top_p": 1.0,
            "eos_id": -1, "logprobs": False, "repetition_penalty": 1.0,
            "stream": None, "t_submit": time.perf_counter(),
            "t_deadline": None, "rid": 0, "warmup": False,
        }

    ids_a = [9, 10, 11, 12, 13, 14, 15, 16, 17, 3]
    ids_b = [21, 22, 23, 24, 25, 26, 27, 28, 29, 5]

    def admit(r):
        eng._start_admission(r)
        while eng._adm is not None:
            eng._run_admission_chunk()

    def drain_to_done(futs, max_dispatches=8):
        for _ in range(max_dispatches):
            if all(f.done() for f in futs):
                break
            eng._run_dispatch()

    try:
        # solo baseline for the survivor's prompt (same engine — same
        # compiled programs, so "bit-identical" is meaningful)
        rb0 = req(ids_b)
        admit(rb0)
        drain_to_done([rb0["future"]])
        solo = rb0["future"].result(timeout=60)["ids"]
        assert len(solo) == 8, solo
        eng._pool.reclaim_all()  # drop registry pins: clean slate
        st = eng._pool.stats()
        assert st["pages_free"] == st["pages_total"], st

        ra, rb = req(ids_a), req(ids_b)
        admit(ra)
        admit(rb)
        assert eng._pool.alloc.free_pages == 0  # overcommitted exactly
        drain_to_done([ra["future"], rb["future"]])
        # slot 0 starved at the page crossing: typed, never a hang
        try:
            ra["future"].result(timeout=60)
            raise AssertionError("overcommitted row did not fail typed")
        except NoFreePages as e:
            assert getattr(e, "status", None) == "no_free_pages", e
        # its freed pages unblocked the neighbour in the same tick
        out_b = rb["future"].result(timeout=60)["ids"]
        assert out_b == solo, (out_b, solo)
        st = eng.stats()
        assert st["kv_decode_page_failures"] == 1, st
        assert st["kv_pages_lazy_allocated"] >= 1, st
        pool = eng._pool
        pool.reclaim_all()
        pst = pool.stats()
        assert pst["pages_free"] == pst["pages_total"], pst
        assert pst["outstanding_page_leases"] == 0, pst
        pool.check_invariants()
        return {
            "starved_typed": True, "survivor_exact": True,
            "pages_leaked": 0,
            "lazy_pages": int(st["kv_pages_lazy_allocated"]),
        }
    finally:
        eng.close()


def _scenario_adaptive_k_switch() -> dict:
    """Scenario 9 — adaptive dispatch depth: controller K switches
    with a stream in flight must move time, never tokens.  The daemon
    runs the serve default (``steps_per_dispatch="adaptive"``).  A
    solo stream's tokens are the baseline; the chaos run re-opens the
    same stream with a slow-resolve fault stretching its dispatches,
    then fires a concurrent burst deep enough to push the controller
    up the ladder while the stream decodes (and back down at the
    quiesce snap).  The survivor's streamed tokens must be
    bit-identical to the solo run, the controller must have actually
    switched inside the window, and the fleet drains clean."""
    d = _Daemon()
    try:
        eng = d.svc.engine
        assert eng.adaptive_k, "serve default must be adaptive"
        base_prompt = [9, 10, 11, 12, 13, 14, 15, 16, 17]
        p = base_prompt + [4]
        toks_solo, _ = d.read_stream(d.open_stream(p, 8))
        d.svc.prefix_cache.flush()
        changes0 = eng.stats()["dispatch_k_changes"]
        # stretch the survivor's dispatches so the burst's controller
        # climb definitely lands while it is mid-stream (scenario 0
        # proved the fault itself is latency-only)
        faults.arm("engine.resolve", flavor="sleep", times=8,
                   seconds=0.1)
        resp = d.open_stream(p, 8)
        # distinct in-vocab tails (vocab_size=64; out-of-range ids
        # would clamp and collapse the burst into identical prompts)
        burst = [
            threading.Thread(
                target=d.generate, args=(base_prompt + [20 + i],),
                daemon=True,
            )
            for i in range(8)
        ]
        for th in burst:
            th.start()
        toks, _ = d.read_stream(resp)
        for th in burst:
            th.join(timeout=120)
        faults.disarm_all()
        assert toks == toks_solo, (toks, toks_solo)
        st = eng.stats()
        k_changes = st["dispatch_k_changes"] - changes0
        assert k_changes > 0, (
            "controller never switched K under the burst"
        )
        assert st["steps_per_dispatch"] in eng.k_ladder, st
        d.assert_drained("adaptive_k_switch")
        return {
            "survivor_exact": True,
            "k_changes": int(k_changes),
            "final_k": st["steps_per_dispatch"],
            "ladder": list(eng.k_ladder),
        }
    finally:
        faults.disarm_all()
        d.close()


def _scenario_replica_kill() -> dict:
    """Scenario 8 — kill one replica of a two-replica fleet mid-stream
    (mlcomp_tpu/fleet: ReplicaManager + prefix-affinity Router, real
    HTTP end to end).  Contract under test:

    - the router stops sending the dead replica traffic within the
      health-poll bound (the first failed proxy marks it down
      immediately; the poll loop confirms);
    - the client-visible damage is BOUNDED: the victim's own in-flight
      stream terminates with an SSE error event — every other request,
      including the re-routed affinity traffic, succeeds with tokens
      bit-identical to baseline (replicas share deterministic toy
      weights, so cross-replica equality is meaningful);
    - the survivor's concurrent stream is bit-identical to its solo
      run;
    - the manager restarts the dead replica within its budget, the
      router re-admits it, and its affinity keys COME HOME (rendezvous
      hashing keys on the stable replica name, not the port), with the
      repeated prefix warming its fresh cache.
    """
    from types import SimpleNamespace

    from mlcomp_tpu.fleet import (
        CallableLauncher,
        ReplicaManager,
        ReplicaSpec,
        Router,
        make_router_http_server,
    )

    daemons: dict = {}
    spawns: list = []

    def close_daemon(d: "_Daemon") -> None:
        for step in (d.harvest_fns, d.httpd.shutdown,
                     d.httpd.server_close, d.svc.close):
            try:
                step()
            except Exception:
                pass

    def spawn(name, port):
        dmn = _Daemon()
        daemons[name] = dmn
        spawns.append(name)
        return SimpleNamespace(
            url=dmn.base, stop=lambda dmn=dmn: close_daemon(dmn)
        )

    mgr = ReplicaManager(
        CallableLauncher(spawn),
        ReplicaSpec(target=2, health_poll_s=0.25,
                    health_timeout_s=1.0, unhealthy_after=2,
                    restart_budget=3),
    )
    router = Router(manager=mgr, health_poll_s=0.2,
                    health_timeout_s=1.0, unhealthy_after=2,
                    saturated_cooldown_s=1.0)
    rhttpd = None
    try:
        mgr.start()
        router.start()
        rhttpd = make_router_http_server(router, "127.0.0.1", 0)
        threading.Thread(
            target=rhttpd.serve_forever, daemon=True
        ).start()
        rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        def wait_live(n, deadline_s=180.0):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < deadline_s:
                if router.status()["live"] >= n:
                    return
                time.sleep(0.1)
            raise AssertionError(
                f"fleet never reached {n} live replicas: "
                f"{router.status()}"
            )

        def generate(ids, n_new=4):
            body = json.dumps(
                {"prompt": list(ids), "max_new_tokens": n_new}
            ).encode()
            req = urllib.request.Request(
                f"{rbase}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    return r.status, json.loads(r.read()), (
                        r.headers.get("x-mlcomp-replica")
                    )
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read()), (
                    e.headers.get("x-mlcomp-replica")
                )

        def open_stream(ids, n_new=8):
            body = {"prompt": list(ids), "max_new_tokens": n_new,
                    "stream": True}
            req = urllib.request.Request(
                f"{rbase}/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            return urllib.request.urlopen(req, timeout=120)

        wait_live(2)
        # find one prompt per replica (different prompts hash to
        # different affinity keys; with two replicas a handful of
        # probes covers both)
        base_prompt = [9, 10, 11, 12, 13, 14, 15, 16, 17]
        by_replica: dict = {}
        baselines: dict = {}
        for i in range(3, 40):
            p = base_prompt + [i]
            code, payload, replica = generate(p)
            assert code == 200, (code, payload)
            if replica not in by_replica:
                by_replica[replica] = p
                baselines[replica] = payload["ids"]
            if len(by_replica) == 2:
                break
        assert len(by_replica) == 2, (
            f"affinity never spread over both replicas: {by_replica}"
        )
        names = sorted(by_replica)
        victim_name, survivor_name = names[0], names[1]
        p_victim = by_replica[victim_name]
        p_survivor = by_replica[survivor_name]
        # affinity is sticky: the same prompt lands on the same replica
        for name, p in by_replica.items():
            code, payload, replica = generate(p)
            assert (code, replica) == (200, name), (code, replica)
            assert payload["ids"] == baselines[name], payload
        # solo survivor stream baseline (streamed tokens, full budget)
        toks_solo, _ = _Daemon.read_stream(open_stream(p_survivor, 8))
        daemons[survivor_name].svc.prefix_cache.flush()

        # open both streams, then KILL the victim replica with its own
        # stream in flight.  The toy decode finishes 8 tokens in tens
        # of ms — far inside the kill window — so a bounded resolve
        # sleep (scenario 0 proved it latency-only) holds both streams
        # open long enough for the kill to land mid-stream.
        faults.arm("engine.resolve", flavor="sleep", times=8,
                   seconds=0.3)
        surv_resp = open_stream(p_survivor, 8)
        vict_resp = open_stream(p_victim, 8)
        t_kill = time.perf_counter()
        close_daemon(daemons[victim_name])
        # victim stream: BOUNDED failure — an SSE error event, a torn
        # connection, or (if the toy decode won the race) a clean
        # finish; never a hang.  That one stream is the whole
        # client-visible cost of losing the replica.
        victim_outcome = "eof"
        try:
            for raw in vict_resp:
                line = raw.decode().strip()
                if line.startswith("data: "):
                    item = json.loads(line[len("data: "):])
                    if "error" in item:
                        victim_outcome = "error_event"
                        break
                    if item.get("done"):
                        victim_outcome = "completed_before_kill"
                        break
        except (OSError, ValueError):
            victim_outcome = "connection_torn"
        vict_resp.close()
        victim_fail_s = time.perf_counter() - t_kill
        assert victim_fail_s < 30, (
            f"victim stream lingered {victim_fail_s:.1f}s"
        )
        # the survivor's concurrent stream is bit-identical to solo
        surv_toks, _ = _Daemon.read_stream(surv_resp)
        faults.disarm_all()
        assert surv_toks == toks_solo, (surv_toks, toks_solo)
        # the router stops routing to the DEAD replica within the
        # health-poll bound: either it observably marks it down, or the
        # manager's restart already replaced the URL (shared compiled
        # programs make a toy respawn ~1 s, so the down window can
        # close before a poll lands) — in both cases no request is
        # routed at the dead socket past the bound, and a request that
        # does hit it conn-refuses into an immediate markdown + retry
        victim_url = {
            r["name"]: r["url"] for r in router.status()["replicas"]
        }.get(victim_name)
        bound_s = (
            router.unhealthy_after * router.health_poll_s
            + router.health_timeout_s + 2.0
        )
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < bound_s:
            reps = {
                r["name"]: r for r in router.status()["replicas"]
            }
            if victim_name not in reps:
                break  # manager cycled it out for restart
            if not reps[victim_name]["live"]:
                break  # observed down
            if reps[victim_name]["url"] != victim_url:
                break  # already restarted on a fresh port
            if spawns.count(victim_name) >= 2:
                break  # restart in flight
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"router still considers {victim_name} live at its "
                f"dead url {bound_s:.1f}s after the kill: "
                f"{router.status()}"
            )
        marked_down_s = time.perf_counter() - t_kill
        # re-routed affinity traffic succeeds NOW, with exact tokens
        # (the fallback replica shares the deterministic weights)
        code, payload, replica = generate(p_victim)
        assert code == 200, (code, payload)
        assert payload["ids"] == baselines[victim_name], payload
        # the manager restarts it and it REJOINS rotation: same name,
        # fresh port, affinity keys come home
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 240:
            if spawns.count(victim_name) >= 2 and (
                router.status()["live"] >= 2
            ):
                break
            time.sleep(0.2)
        assert spawns.count(victim_name) >= 2, (
            f"manager never restarted {victim_name}: {mgr.stats()}"
        )
        wait_live(2)
        code, payload, replica = generate(p_victim)
        assert (code, replica) == (200, victim_name), (code, replica)
        assert payload["ids"] == baselines[victim_name], payload
        # repeated prefix warms the rejoined replica's fresh cache
        daemons[victim_name].svc.prefix_cache.flush()
        code, payload, replica = generate(p_victim)
        assert (code, replica) == (200, victim_name), (code, replica)
        assert payload.get("cache_hit_tokens", 0) > 0, payload
        st = router.status()
        assert st["counts"]["reason"]["affinity"] > 0, st["counts"]
        return {
            "victim_outcome": victim_outcome,
            "victim_failed_in_s": round(victim_fail_s, 2),
            "marked_down_in_s": round(marked_down_s, 2),
            "survivor_exact": True,
            "restarts": mgr.stats()["restarts"]["unhealthy"],
            "rejoined": True,
        }
    finally:
        if rhttpd is not None:
            rhttpd.shutdown()
            rhttpd.server_close()
        router.close()
        mgr.close(stop_replicas=True)


def _scenario_prefill_kill_mid_transfer() -> dict:
    """Scenario 10 — a phase-split fleet's prefill replica dies
    MID-TRANSFER (mlcomp_tpu/fleet two-hop handoff, real HTTP end to
    end).  Contract under test:

    - the router's hop-1 read of the handoff blob comes up SHORT
      (Content-Length promised more bytes than arrived); the router
      marks the victim down and retries the whole hop on the
      surviving prefill replica — the client sees one 200 with tokens
      bit-identical to the monolithic baseline, never a torn blob;
    - a partial blob that reaches a decode replica directly is
      rejected TYPED (400 ``bad_handoff``) before any page, lease, or
      slot is touched — the pool's free count is unchanged and the
      reject is counted;
    - the intact blob still imports cleanly afterwards, and at
      quiesce both sides hold zero leaked pages/leases/slots.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from mlcomp_tpu.fleet import Router, make_router_http_server

    prompts = [
        [9, 10, 11, 12, 13, 14, 15, 16, 17, p] for p in range(3, 23)
    ]
    mono = _Daemon()
    baseline = {}
    try:
        for p in prompts[:6]:
            code, payload = mono.generate(p)
            assert code == 200, (code, payload)
            baseline[tuple(p)] = payload["ids"]
    finally:
        mono.close()

    pre = _Daemon(phase="prefill", kv_layout="dense",
                  max_slots=None, kv_pages=None)
    dec = _Daemon(phase="decode")
    holder = {"blob": b"", "kills": 0}

    class _DyingPrefill(BaseHTTPRequestHandler):
        """The victim: answers /healthz as a live prefill replica,
        then dies halfway through every /prefill body."""

        def log_message(self, *a):
            pass

        def do_GET(self):  # noqa: N802
            body = json.dumps({
                "ok": True, "ready": True, "phase": "prefill",
                "queue_depth": 0,
            }).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            blob = holder["blob"]
            self.send_response(200)
            self.send_header(
                "Content-Type", "application/octet-stream"
            )
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob[: max(1, len(blob) // 2)])
            holder["kills"] += 1
            try:
                self.wfile.flush()
                self.connection.close()  # mid-transfer death
            except OSError:
                pass

    victim = ThreadingHTTPServer(("127.0.0.1", 0), _DyingPrefill)
    threading.Thread(target=victim.serve_forever, daemon=True).start()
    victim_url = f"http://127.0.0.1:{victim.server_address[1]}"
    router = Router(
        urls=[victim_url, pre.base, dec.base],
        health_poll_s=0.2, health_timeout_s=5.0,
    )
    rhttpd = None
    try:
        # seed the victim's Content-Length with a REAL blob size
        req = urllib.request.Request(
            f"{pre.base}/prefill",
            data=json.dumps({"prompt": prompts[0],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            holder["blob"] = r.read()
        router.poll_once()
        assert router.phase_split_active(), router.status()
        rhttpd = make_router_http_server(router, "127.0.0.1", 0)
        threading.Thread(
            target=rhttpd.serve_forever, daemon=True
        ).start()
        rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        def generate(ids):
            body = json.dumps(
                {"prompt": list(ids), "max_new_tokens": 4}
            ).encode()
            rq = urllib.request.Request(
                f"{rbase}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(rq, timeout=120) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        # drive until some prompt's hop 1 lands on the victim (HRW
        # spreads keys over both prefill replicas); EVERY request —
        # including the one the victim truncated — must come back 200
        # and bit-identical via the survivor
        served = 0
        for p in prompts:
            code, payload = generate(p)
            assert code == 200, (code, payload)
            if tuple(p) in baseline:
                assert payload["ids"] == baseline[tuple(p)], payload
            served += 1
            if holder["kills"] >= 1:
                break
        assert holder["kills"] >= 1, (
            f"affinity never routed hop 1 at the victim over "
            f"{served} prompts"
        )
        st = router.status()
        assert st["counts"]["handoffs"] == served, st["counts"]
        assert st["counts"]["handoff_failures"] == 0, st["counts"]
        assert st["counts"]["outcome"]["upstream_error"] >= 1
        victim_name = victim_url.split("://", 1)[-1]
        reps = {r["name"]: r for r in st["replicas"]}
        assert not reps[victim_name]["live"], reps

        # the engine.export chaos point: a fault while the prefill
        # replica captures/serializes the handoff fails ONLY that
        # request (500 with the typed error), and the next /prefill on
        # the same daemon succeeds — admission-scoped, like the
        # insert-path faults
        faults.arm("engine.export", flavor="raise", times=1)
        req = urllib.request.Request(
            f"{pre.base}/prefill",
            data=json.dumps({"prompt": prompts[1],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                raise AssertionError(
                    f"armed export fault answered {r.status}"
                )
        except urllib.error.HTTPError as e:
            verdict = json.loads(e.read())
            assert e.code == 500, (e.code, verdict)
            assert "FaultInjected" in verdict["error"], verdict
        finally:
            faults.disarm_all()
        req = urllib.request.Request(
            f"{pre.base}/prefill",
            data=json.dumps({"prompt": prompts[1],
                             "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200

        # decode-side typed reject of the partial blob, zero touched
        # (quiesce FIRST: the brokered requests' slot retirements land
        # on the loop thread a boundary after their responses, so the
        # free-count only settles once the fleet drains)
        dec.assert_drained("pre_partial_import")
        eng = dec.svc.engine
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            pst = eng._pool.stats()
            if pst["pages_used"] == pst["pages_reclaimable"]:
                break
            time.sleep(0.05)
        pool_free0 = eng._pool.stats()["pages_free"]
        rejects0 = eng.stats()["handoff_rejects"]
        blob = holder["blob"]
        req = urllib.request.Request(
            f"{dec.base}/import", data=blob[: len(blob) // 2],
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                raise AssertionError(
                    f"partial import accepted: {r.status}"
                )
        except urllib.error.HTTPError as e:
            verdict = json.loads(e.read())
            assert e.code == 400, (e.code, verdict)
            assert verdict["status"] == "bad_handoff", verdict
        pst = eng._pool.stats()
        assert pst["pages_free"] == pool_free0, pst
        assert pst["outstanding_page_leases"] == 0, pst
        assert eng.stats()["handoff_rejects"] == rejects0 + 1
        # the INTACT blob still imports, bit-identical
        req = urllib.request.Request(
            f"{dec.base}/import", data=blob,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            payload = json.loads(r.read())
        assert payload["ids"] == baseline[tuple(prompts[0])], payload

        # quiesce: nothing leaked on either side (poll the POOL's own
        # state — the response resolves a beat before the loop thread
        # releases the slot's pages)
        dec.assert_drained("prefill_kill_mid_transfer")
        pre.assert_drained("prefill_kill_mid_transfer")
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 10:
            pst = eng._pool.stats()
            if pst["pages_used"] == pst["pages_reclaimable"]:
                break
            time.sleep(0.05)
        assert pst["pages_used"] == pst["pages_reclaimable"], pst
        assert pst["pages_free"] + pst["pages_used"] == (
            pst["pages_total"]
        ), pst
        return {
            "kills": holder["kills"],
            "served_exact": served,
            "import_reject": "typed_400_bad_handoff",
            "leaked_pages": 0,
            "retried_via_survivor": True,
        }
    finally:
        if rhttpd is not None:
            rhttpd.shutdown()
            rhttpd.server_close()
        router.close()
        victim.shutdown()
        victim.server_close()
        pre.close()
        dec.close()


def main(argv=None) -> int:
    out = run()
    print(f"ok: {json.dumps(out)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

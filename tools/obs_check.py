#!/usr/bin/env python
"""Observability smoke harness: serve-path /metrics + /trace, checked.

Spins up a toy continuous engine behind the real serve daemon HTTP
stack (``serve.make_http_server`` on an ephemeral port, prefix cache
on), drives real requests through ``POST /generate``, then asserts the
observability contract the docs promise (docs/observability.md):

- ``GET /metrics`` parses as Prometheus text exposition: every sample
  line well-formed, every sample family preceded by exactly one
  ``# TYPE``, histogram ``_bucket`` series cumulative and capped by
  ``_count``;
- every DOCUMENTED serve-daemon metric is present (a metric renamed in
  code but not in docs — or vice versa — fails here, not in a user's
  dashboard);
- counters are MONOTONIC across two scrapes with traffic in between,
  and the traffic actually moved the request counter;
- ``GET /trace`` returns Chrome trace-event JSON (Perfetto-loadable):
  dispatch async begin/end pairs balance, issue/resolve spans exist,
  request lifecycle spans carry matched begin/ends, and ``last_ms``
  windowing returns a subset;
- ``GET /profile?dispatches=N`` completes against live traffic and
  returns the device-time attribution contract (device_time_ms,
  host_gap_ms, kernel breakdown, per-family roofline utilization),
  and the ``/trace`` fetched AFTER it carries the merged
  ``engine.device`` track aligned with the dispatch spans;
- the observability SPINE: ``GET /slo`` answers the default
  objectives' burn-rate/breach shape, ``GET /metrics/history`` serves
  the ring with non-negative (reset-clamped) counter deltas that sum
  to no more than the lifetime totals, a request that arrives with a
  W3C ``traceparent`` echoes its trace id and
  ``GET /trace?trace_id=`` / ``?rid=`` return exactly that request's
  events;
- the FLEET: a second toy daemon, adopted with the first into a
  two-replica set by the fleet ReplicaManager (mlcomp_tpu/fleet) and
  fronted by the prefix-affinity Router; a report server scraping the
  manager's DYNAMIC registry (``MLCOMP_TPU_SERVE_REGISTRY``) serves
  ONE merged ``/fleet/trace`` with one pid per daemon (named,
  clock-aligned) and one ``/fleet/metrics`` exposition with a
  ``daemon`` label per sample.  End to end through the router: a
  traced request's spans land under the replica that served it,
  a repeated prefix re-lands on its affinity replica and HITS its
  warmed cache (cache-hit-token counters prove it), every documented
  ``mlcomp_fleet_*`` family scrapes clean from the router's
  ``/metrics``, and the autoscaler's decision log responds to an
  injected burn-rate breach without moving the dry-run target.

No TPU needed (CPU jax), finishes in seconds; tests/test_obs_check.py
wires it into tier-1 like tools/cachecheck.py.  Standalone:

    python tools/obs_check.py
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the serve-daemon metric families docs/observability.md documents —
# keep the three in sync (this harness is the enforcement)
DOCUMENTED_SERVE_METRICS = [
    "mlcomp_engine_requests_total",
    "mlcomp_engine_dispatches_total",
    "mlcomp_engine_steps_total",
    "mlcomp_engine_emitted_tokens_total",
    "mlcomp_engine_prefills_total",
    "mlcomp_engine_prefill_chunks_total",
    "mlcomp_engine_fused_prefill_chunks_total",
    "mlcomp_engine_admissions_overlapped_total",
    "mlcomp_engine_admission_stall_ms",
    "mlcomp_engine_latency_samples_total",
    "mlcomp_engine_slots",
    "mlcomp_engine_active_slots",
    "mlcomp_engine_queue_depth",
    "mlcomp_engine_pipeline_depth",
    "mlcomp_engine_pipeline_inflight",
    "mlcomp_engine_pipeline_peak_inflight",
    "mlcomp_engine_pipeline_issued_total",
    "mlcomp_engine_pipeline_hidden_ms_total",
    "mlcomp_engine_pipeline_wait_ms_total",
    "mlcomp_engine_pipeline_overlap_efficiency",
    "mlcomp_engine_dispatch_k",
    "mlcomp_engine_dispatch_k_changes_total",
    "mlcomp_engine_trace_events_dropped_total",
    "mlcomp_engine_ttft_ms",
    "mlcomp_engine_per_token_ms",
    "mlcomp_engine_device_time_ms",
    "mlcomp_engine_device_time_ms_per_dispatch",
    "mlcomp_engine_host_overhead_ms_per_dispatch",
    "mlcomp_engine_roofline_utilization",
    "mlcomp_engine_profile_captures_total",
    "mlcomp_engine_healthy",
    "mlcomp_engine_kv_pages_total",
    "mlcomp_engine_kv_pages_free",
    "mlcomp_engine_kv_pages_shared",
    "mlcomp_engine_kv_page_cow_forks_total",
    "mlcomp_engine_slots_scaled_total",
    "mlcomp_engine_live_slots",
    "mlcomp_engine_max_slots",
    "mlcomp_engine_kv_registry_hits_total",
    "mlcomp_engine_kv_registry_hit_tokens_total",
    "mlcomp_engine_kv_bytes_moved_per_dispatch",
    "mlcomp_engine_kv_pages_lazy_allocated_total",
    "mlcomp_engine_kv_decode_page_failures_total",
    "mlcomp_engine_handoffs_imported_total",
    "mlcomp_engine_kv_pages_imported_total",
    "mlcomp_engine_handoff_bytes_imported_total",
    "mlcomp_engine_handoff_rejects_total",
    "mlcomp_engine_deadline_exceeded_total",
    "mlcomp_engine_cancelled_total",
    "mlcomp_engine_watchdog_stalls_total",
    "mlcomp_engine_watchdog_restarts_total",
    "mlcomp_cache_degraded_total",
    "mlcomp_serving_requests_rejected_total",
    "mlcomp_service_info",
    "mlcomp_service_batches_total",
    "mlcomp_service_batched_rows_total",
    "mlcomp_prefix_cache_lookups_total",
    "mlcomp_prefix_cache_hits_total",
    "mlcomp_prefix_cache_misses_total",
    "mlcomp_prefix_cache_matched_tokens_total",
    "mlcomp_prefix_cache_used_hits_total",
    "mlcomp_prefix_cache_used_hit_tokens_total",
    "mlcomp_prefix_cache_inserted_tokens_total",
    "mlcomp_prefix_cache_evictions_total",
    "mlcomp_prefix_cache_evicted_tokens_total",
    "mlcomp_prefix_cache_insert_errors_total",
    "mlcomp_prefix_cache_insert_dropped_total",
    "mlcomp_prefix_cache_bytes",
    "mlcomp_prefix_cache_max_bytes",
    "mlcomp_prefix_cache_nodes",
    "mlcomp_prefix_cache_pinned_nodes",
    "mlcomp_prefix_cache_outstanding_leases",
    "mlcomp_prefix_cache_capture_queue_depth",
    "mlcomp_metrics_history_samples_total",
    "mlcomp_metrics_history_span_seconds",
    "mlcomp_slo_burn_rate",
    "mlcomp_slo_breached",
    "mlcomp_slo_breaches_total",
]

# the fleet control-plane families docs/observability.md documents
# (rendered by the ROUTER's /metrics — manager, router, and autoscaler
# share one registry); graftcheck's drift pass keeps this list, the
# docs catalog, and the mlcomp_tpu/fleet/ collectors in three-way sync
DOCUMENTED_FLEET_METRICS = [
    "mlcomp_fleet_replicas_target",
    "mlcomp_fleet_replicas_live",
    "mlcomp_fleet_replica_restarts_total",
    "mlcomp_fleet_router_requests_total",
    "mlcomp_fleet_router_routed_total",
    "mlcomp_fleet_router_upstream_retries_total",
    "mlcomp_fleet_router_replicas_live",
    "mlcomp_fleet_autoscale_decisions_total",
    "mlcomp_fleet_replicas_live_by_phase",
    "mlcomp_fleet_router_handoffs_total",
    "mlcomp_fleet_router_handoff_failures_total",
    "mlcomp_fleet_router_handoff_bytes_total",
    "mlcomp_fleet_router_handoff_ms",
    "mlcomp_fleet_router_conn_opens_total",
    "mlcomp_fleet_router_conn_reuses_total",
]

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.eE+\-]+|\+Inf|NaN)$"
)
_LABELS_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """Lint + parse Prometheus text format.  Returns
    ``(samples, types)``: ``samples`` maps sample name (including
    ``_bucket``/``_sum``/``_count`` suffixes) -> {labelstring: value},
    ``types`` maps family name -> type.  Raises AssertionError on any
    malformed line or a sample without a preceding # TYPE."""
    samples: dict = {}
    types: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"malformed TYPE line: {line!r}"
            name, kind = parts[2], parts[3]
            assert kind in ("counter", "gauge", "histogram", "untyped"), line
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        family = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in types or family in types, (
            f"sample {name} has no # TYPE"
        )
        if labels:
            body = labels[1:-1]
            rebuilt = ",".join(
                f'{k}="{v}"' for k, v in _LABELS_RE.findall(body)
            )
            assert rebuilt == body, f"malformed labels: {labels!r}"
        v = float(value.replace("+Inf", "inf"))
        samples.setdefault(name, {})[labels] = v
    return samples, types


def check_histograms(samples, types):
    """Cumulative-bucket sanity for every histogram family."""
    for family, kind in types.items():
        if kind != "histogram":
            continue
        buckets = samples.get(f"{family}_bucket", {})
        counts = samples.get(f"{family}_count", {})
        assert buckets and counts, f"{family}: empty histogram"
        # group bucket series by their non-le labels
        by_group: dict = {}
        for labels, v in buckets.items():
            body = labels[1:-1] if labels else ""
            pairs = dict(_LABELS_RE.findall(body))
            le = pairs.pop("le")
            key = tuple(sorted(pairs.items()))
            by_group.setdefault(key, []).append((le, v))
        for key, series in by_group.items():
            inf = [v for le, v in series if le == "+Inf"]
            assert inf, f"{family}{key}: no +Inf bucket"
            finite = sorted(
                ((float(le), v) for le, v in series if le != "+Inf")
            )
            last = 0.0
            for _, v in finite:
                assert v >= last, f"{family}{key}: non-cumulative buckets"
                last = v
            assert inf[0] >= last, f"{family}{key}: +Inf below last bucket"


def _counters_monotonic(before, after, types):
    for family, kind in types.items():
        if kind != "counter":
            continue
        for labels, v0 in before.get(family, {}).items():
            v1 = after.get(family, {}).get(labels)
            assert v1 is not None, f"counter {family}{labels} vanished"
            assert v1 >= v0, (
                f"counter {family}{labels} went backwards: {v0} -> {v1}"
            )


def run(n_requests: int = 3) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from mlcomp_tpu.models import create_model
    from mlcomp_tpu.serve import GenerationService, make_http_server
    from mlcomp_tpu.train.state import init_model

    model = create_model({
        "name": "transformer_lm", "vocab_size": 64, "hidden": 32,
        "layers": 1, "heads": 2, "mlp_dim": 64, "dtype": "float32",
    })
    prompt = jnp.asarray(np.random.RandomState(0).randint(1, 64, (1, 8)))
    params, _ = init_model(model, {"x": prompt}, jax.random.PRNGKey(0))
    # prefill_chunk 8 divides the 16 bucket, so the prefix cache's hit
    # path (and its metrics) can actually engage on repeated prompts;
    # the PAGED KV layout (kvpool) runs live so its gauge/counter
    # families — pool occupancy, COW forks, elastic slot scaling, the
    # device prefix registry — are asserted against real traffic too
    svc = GenerationService(
        model, {"params": params}, batch_sizes=(1, 2),
        prompt_buckets=(16,), max_new_buckets=(8,),
        prefix_cache=True, prefill_chunk=8,
        kv_layout="paged", max_slots=4, kv_pages=2 + 64,
        # a fast history cadence so the spine surfaces (/slo,
        # /metrics/history, the mlcomp_slo_*/history families) carry
        # real samples within this harness's lifetime
        metrics_history_interval=0.25,
    )
    httpd = make_http_server(svc, "127.0.0.1", 0, "obs-check")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"

    def generate(ids, max_new=4, headers=None, at=None):
        body = json.dumps(
            {"prompt": ids, "max_new_tokens": max_new}
        ).encode()
        req = urllib.request.Request(
            f"{at or base}/generate", data=body,
            headers={"Content-Type": "application/json",
                     **(headers or {})},
        )
        with urllib.request.urlopen(req, timeout=600) as r:
            return json.loads(r.read())

    def get(path, at=None):
        with urllib.request.urlopen(
            f"{at or base}{path}", timeout=60
        ) as r:
            return r.read()

    try:
        shared = [9, 10, 11, 12, 13, 14, 15, 16, 17]
        for i in range(n_requests):
            out = generate(shared + [i + 1])
            assert len(out["ids"]) == 4, out
        svc.prefix_cache.flush()

        # device-profile capture BEFORE the first scrape: the capture
        # feeds mlcomp_engine_device_time_ms and flips the roofline
        # gauges to capture-sourced, so the documented-metric check
        # below sees every family.  The window is dispatch-gated, so
        # traffic must flow while the request waits — pump generates
        # until it resolves.
        prof_res: dict = {}

        def _arm_profile():
            try:
                with urllib.request.urlopen(
                    f"{base}/profile?dispatches=2", timeout=300
                ) as r:
                    prof_res["code"] = r.status
                    prof_res["body"] = json.loads(r.read())
            except Exception as e:
                prof_res["error"] = repr(e)

        th = threading.Thread(target=_arm_profile, daemon=True)
        th.start()
        pumped = 0
        while th.is_alive() and pumped < 64:
            generate(shared + [50 + pumped])
            pumped += 1
        th.join(timeout=120)
        assert prof_res.get("code") == 200, prof_res
        att = prof_res["body"]
        for key in ("dispatches", "device_time_ms", "host_gap_ms",
                    "device_time_ms_per_dispatch", "kernels", "families",
                    "roofline_ms_per_dispatch", "roofline_utilization"):
            assert key in att, f"/profile missing {key!r}: {sorted(att)}"
        assert att["dispatches"] >= 1
        assert att["device_time_ms"] > 0
        assert att["kernels"] and att["families"]
        for fam in att["families"].values():
            for key in ("dispatches", "device_time_ms", "host_gap_ms",
                        "roofline_utilization"):
                assert key in fam, fam
        # one capture at a time: a second request while nothing is
        # armed must NOT 409 (the slot freed) — but arming twice does.
        # (the live 409 is covered by tests/test_serve.py; here we just
        # assert the slot is free again)
        assert svc.engine._profile is None

        # a deterministic history sample before the first scrape: the
        # SLO gauges and history families materialize at the first
        # sampler tick, and the documented-metric check below must see
        # every family
        svc.history.sample_now()
        text1 = get("/metrics").decode()
        s1, t1 = parse_exposition(text1)
        check_histograms(s1, t1)
        missing = [
            m for m in DOCUMENTED_SERVE_METRICS
            if m not in t1
        ]
        assert not missing, f"documented metrics absent: {missing}"
        req0 = s1["mlcomp_engine_requests_total"][""]

        for i in range(n_requests):
            generate(shared + [100 + i])
            # a different LENGTH: same prefix at a different placement
            # misses the placement-exact device registry and exercises
            # the HOST prefix-cache tier (token-indexed, re-placed)
            generate(shared + [100 + i, 7])
        # FULL-budget decodes: max_new 8 pushes the write span past
        # the insert's one-dispatch lookahead, so the fused paged
        # engine allocates its last decode page LAZILY mid-stream —
        # the counter asserted below
        for i in range(2):
            out = generate(shared + [200 + i], max_new=8)
            assert len(out["ids"]) == 8, out
        text2 = get("/metrics").decode()
        s2, t2 = parse_exposition(text2)
        check_histograms(s2, t2)
        _counters_monotonic(s1, s2, t1)
        req1 = s2["mlcomp_engine_requests_total"][""]
        assert req1 == req0 + 2 * n_requests + 2, (req0, req1)
        assert s2["mlcomp_prefix_cache_hits_total"][""] > 0
        # paged-KV pool gauges carry live occupancy, and the device
        # registry tier absorbed the same-placement repeats
        kv_total = s2["mlcomp_engine_kv_pages_total"][""]
        kv_free = s2["mlcomp_engine_kv_pages_free"][""]
        assert kv_total > 0 and 0 <= kv_free <= kv_total
        assert s2["mlcomp_engine_kv_registry_hits_total"][""] > 0
        assert s2["mlcomp_engine_live_slots"][""] >= 1
        # fused paged attention (the daemon's default data path):
        # the bytes-moved gauge is live, the full-budget decodes above
        # allocated decode pages lazily, and nothing starved
        assert s2["mlcomp_engine_kv_bytes_moved_per_dispatch"][""] >= 0
        assert s2["mlcomp_engine_kv_pages_lazy_allocated_total"][""] > 0
        assert s2["mlcomp_engine_kv_decode_page_failures_total"][""] == 0

        # ---- adaptive dispatch depth: the daemon runs the serve
        # default (steps_per_dispatch="adaptive"), so the dispatch_k
        # gauge must sit on the ladder — and a CONCURRENT burst (queue
        # deeper than the slot pool) must move the controller off the
        # quiesce floor: the changes counter advances and the gauge
        # still reads a ladder rung afterwards
        assert svc.engine.adaptive_k, "serve default should be adaptive"
        ladder = set(svc.engine.k_ladder)
        assert s2["mlcomp_engine_dispatch_k"][""] in ladder, (
            s2["mlcomp_engine_dispatch_k"], ladder
        )
        changes0 = s2["mlcomp_engine_dispatch_k_changes_total"][""]
        # distinct in-vocab tails (vocab_size=64: an out-of-range id
        # would clamp in the embedding gather and collapse the burst
        # into 8 copies of one prompt)
        burst_threads = [
            threading.Thread(
                target=lambda i=i: generate(shared + [40 + i],
                                            max_new=8),
                daemon=True,
            )
            for i in range(8)
        ]
        for th2 in burst_threads:
            th2.start()
        for th2 in burst_threads:
            th2.join(timeout=300)
        s2b, t2b = parse_exposition(get("/metrics").decode())
        assert s2b["mlcomp_engine_dispatch_k"][""] in ladder
        assert (
            s2b["mlcomp_engine_dispatch_k_changes_total"][""] > changes0
        ), "adaptive-K gauge never moved under the burst"

        trace = json.loads(get("/trace?last_ms=600000"))
        evs = trace["traceEvents"]
        assert isinstance(evs, list) and evs, "empty trace"
        for e in evs:
            assert "ph" in e and "pid" in e, e
        begins = sum(
            1 for e in evs if e["ph"] == "b" and e["name"] == "dispatch"
        )
        ends = sum(
            1 for e in evs if e["ph"] == "e" and e["name"] == "dispatch"
        )
        assert begins and begins == ends, (begins, ends)
        names = {e["name"] for e in evs}
        for want in ("issue", "resolve", "request", "first_token",
                     "prefill_chunk", "insert", "prefix_cache.lookup",
                     "kv_registry.lookup"):
            assert want in names, f"missing trace span {want!r}"
        # the /profile capture merged a DEVICE track: a named
        # engine.device thread whose complete spans sit inside the
        # capture window — host spans render aligned above them
        track_tids = {
            e["args"]["name"]: e["tid"] for e in evs
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "engine.device" in track_tids, sorted(track_tids)
        dev_evs = [
            e for e in evs
            if e.get("tid") == track_tids["engine.device"]
            and e["ph"] == "X"
        ]
        assert dev_evs, "device track carries no spans"
        for e in dev_evs:
            assert e.get("dur", 0) >= 0 and "ts" in e, e
        # alignment: the device spans overlap the host dispatch span
        # range (both sit on the recorder clock)
        disp_ts = [
            e["ts"] for e in evs
            if e.get("cat") == "disp" and e["ph"] in ("b", "e")
        ]
        dev_lo = min(e["ts"] for e in dev_evs)
        dev_hi = max(e["ts"] + e.get("dur", 0) for e in dev_evs)
        assert disp_ts and dev_lo <= max(disp_ts) and (
            dev_hi >= min(disp_ts)
        ), "device track does not overlap the dispatch spans"
        assert "device_capture" in names
        # last_ms windows: a zero-width trailing window drops the
        # decode-time events the full fetch carried
        tiny = json.loads(get("/trace?last_ms=0.001"))
        assert len(tiny["traceEvents"]) <= len(evs)

        # ---- observability spine: /slo against the default objectives
        slo = json.loads(get("/slo"))
        assert slo["evaluations"] >= 1, slo
        assert set(slo["slos"]) == {
            "ttft_p95", "per_token_p50", "reject_rate", "engine_healthy"
        }, sorted(slo["slos"])
        for name, st in slo["slos"].items():
            assert set(st["burn_rate"]) == {"fast", "slow"}, (name, st)
            assert all(v >= 0 for v in st["burn_rate"].values()), st
            assert isinstance(st["breached"], bool), st
        # nothing was rejected and the engine never went unhealthy:
        # those objectives cannot be burning.  The toy LATENCY SLOs may
        # legitimately breach (first-request compile TTFT blows a 2 s
        # objective) — that is the burn math working, not a failure.
        for name in ("reject_rate", "engine_healthy"):
            assert not slo["slos"][name]["breached"], slo["slos"][name]
        assert set(slo["breached"]) <= {"ttft_p95", "per_token_p50"}
        hz = json.loads(get("/healthz"))
        assert hz["slo"]["breached"] == slo["breached"], hz["slo"]
        assert hz["metrics_history"]["samples_taken"] >= 1

        # ---- /metrics/history: reset-clamped deltas vs lifetime totals
        svc.history.sample_now()  # tail sample carrying today's traffic
        hist = json.loads(get("/metrics/history?window_s=600"))
        assert hist["samples"], hist
        key = "mlcomp_engine_requests_total"
        deltas = [s["counters"].get(key, 0.0) for s in hist["samples"]]
        assert all(d >= 0 for d in deltas), deltas
        assert 0 < sum(deltas) <= hist["totals"][key], (
            deltas, hist["totals"].get(key)
        )
        assert any(
            (s["quantiles"].get("mlcomp_engine_ttft_ms") or {}).get("p50")
            is not None
            for s in hist["samples"]
        ), "no materialized TTFT quantile in any window sample"

        # ---- trace-id propagation: inherit a traceparent, echo it,
        #      filter the flight recorder down to that one request
        tid = "0af7651916cd43dd8448eb211c80319c"
        out = generate(shared + [240], headers={
            "traceparent": f"00-{tid}-00f067aa0ba902b7-01",
        })
        assert out["trace_id"] == tid, out
        filt = json.loads(get(f"/trace?trace_id={tid}"))
        rids = filt["otherData"]["filter"]["rids"]
        assert len(rids) == 1, rids
        rid = rids[0]
        non_meta = [e for e in filt["traceEvents"] if e["ph"] != "M"]
        assert non_meta, "trace-id filter returned nothing"
        for e in non_meta:
            args = e.get("args") or {}
            assert (
                (e.get("cat") == "req" and e.get("id") == str(rid))
                or args.get("rid") == rid
                or args.get("trace_id") == tid
            ), e
        fnames = {e["name"] for e in non_meta}
        assert {"request", "insert"} <= fnames, sorted(fnames)
        by_rid = json.loads(get(f"/trace?rid={rid}"))
        assert len(by_rid["traceEvents"]) == len(filt["traceEvents"])

        # ---- disaggregation: a prefill service exports a KV-page
        #      handoff, the MAIN (paged) daemon imports it via POST
        #      /import, and both sides' handoff metric families carry
        #      the traffic (docs/observability.md catalog rows)
        pre_svc = GenerationService(
            model, {"params": params}, batch_sizes=(1, 2),
            prompt_buckets=(16,), max_new_buckets=(8,),
            prefill_chunk=8, phase="prefill",
        )
        pre_httpd = make_http_server(
            pre_svc, "127.0.0.1", 0, "obs-prefill"
        )
        threading.Thread(
            target=pre_httpd.serve_forever, daemon=True
        ).start()
        pre_base = f"http://127.0.0.1:{pre_httpd.server_address[1]}"
        try:
            body = json.dumps({
                "prompt": shared + [77], "max_new_tokens": 4,
            }).encode()
            req = urllib.request.Request(
                f"{pre_base}/prefill", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=600) as r:
                blob = r.read()
                assert r.headers["Content-Type"] == (
                    "application/octet-stream"
                )
            req = urllib.request.Request(
                f"{base}/import", data=blob,
                headers={"Content-Type": "application/octet-stream"},
            )
            with urllib.request.urlopen(req, timeout=600) as r:
                imp = json.loads(r.read())
            assert len(imp["ids"]) == 4, imp
            # a truncated blob rejects typed — and is COUNTED
            req = urllib.request.Request(
                f"{base}/import", data=blob[: len(blob) // 2],
                headers={"Content-Type": "application/octet-stream"},
            )
            try:
                urllib.request.urlopen(req, timeout=600)
                raise AssertionError("partial import accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 400, e.code
                assert json.loads(e.read())["status"] == "bad_handoff"
            ds, dt = parse_exposition(get("/metrics").decode())
            for fam, least in (
                ("mlcomp_engine_handoffs_imported_total", 1),
                ("mlcomp_engine_kv_pages_imported_total", 1),
                ("mlcomp_engine_handoff_bytes_imported_total", 1),
                ("mlcomp_engine_handoff_rejects_total", 1),
            ):
                assert ds[fam][""] >= least, (fam, ds.get(fam))
            es, et = parse_exposition(
                get("/metrics", at=pre_base).decode()
            )
            for fam in (
                "mlcomp_engine_handoffs_exported_total",
                "mlcomp_engine_kv_pages_exported_total",
                "mlcomp_engine_handoff_bytes_exported_total",
            ):
                assert es[fam][""] >= 1, (fam, es.get(fam))
            hz_pre = json.loads(get("/healthz", at=pre_base))
            assert hz_pre["phase"] == "prefill", hz_pre
            disagg_imports = int(
                ds["mlcomp_engine_handoffs_imported_total"][""]
            )
        finally:
            pre_httpd.shutdown()
            pre_httpd.server_close()
            pre_svc.close()

        # ---- the fleet: a second daemon behind a managed router +
        #      a report server scraping the DYNAMIC registry -> one
        #      merged Perfetto trace, one labeled exposition, affinity
        #      verified by cache-hit counters, autoscaler decision log
        import tempfile
        from types import SimpleNamespace

        from mlcomp_tpu.fleet import (
            Autoscaler,
            AutoscalePolicy,
            CallableLauncher,
            ReplicaManager,
            ReplicaSpec,
            Router,
            make_router_http_server,
        )
        from mlcomp_tpu.obs.metrics import Registry as ObsRegistry
        from mlcomp_tpu.report.server import start_in_thread

        svc2 = GenerationService(
            model, {"params": params}, batch_sizes=(1,),
            prompt_buckets=(16,), max_new_buckets=(8,),
            prefix_cache=True, prefill_chunk=8,
            metrics_history_interval=0,
        )
        httpd2 = make_http_server(svc2, "127.0.0.1", 0, "obs-check-2")
        threading.Thread(
            target=httpd2.serve_forever, daemon=True
        ).start()
        base2 = f"http://127.0.0.1:{httpd2.server_address[1]}"
        saved_env = {
            k: os.environ.get(k)
            for k in ("MLCOMP_TPU_SERVE_URLS", "MLCOMP_TPU_SERVE_URL",
                      "MLCOMP_TPU_SERVE_REGISTRY")
        }
        report_srv = None
        mgr = router = rhttpd = None
        try:
            generate([3, 4, 5, 6], at=base2)
            # the manager adopts both daemons as a two-replica set and
            # publishes them into the JSON registry the report server
            # reads (MLCOMP_TPU_SERVE_URLS' dynamic successor; the env
            # var remains the static fallback)
            reg_path = tempfile.mktemp(suffix=".json")
            fleet_urls = {"fleet-0": base, "fleet-1": base2}
            fleet_svcs = {"fleet-0": svc, "fleet-1": svc2}
            fleet_reg = ObsRegistry()
            mgr = ReplicaManager(
                CallableLauncher(lambda name, port: SimpleNamespace(
                    url=fleet_urls[name], stop=lambda: None,
                )),
                ReplicaSpec(target=2, health_poll_s=0.2),
                metrics=fleet_reg, registry_path=reg_path,
            )
            mgr.tick()
            assert mgr.stats()["live"] == 2, mgr.stats()
            router = Router(manager=mgr, metrics=fleet_reg,
                            health_poll_s=0.2)
            router.poll_once()
            scaler = Autoscaler(
                AutoscalePolicy(min_replicas=1, max_replicas=4,
                                sustain_s=0.0, cooldown_s=0.0),
                manager=mgr, metrics=fleet_reg, dry_run=True,
            )
            rhttpd = make_router_http_server(router, "127.0.0.1", 0)
            threading.Thread(
                target=rhttpd.serve_forever, daemon=True
            ).start()
            rrbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"
            os.environ.pop("MLCOMP_TPU_SERVE_URLS", None)
            os.environ["MLCOMP_TPU_SERVE_REGISTRY"] = reg_path
            report_srv, rport = start_in_thread(
                tempfile.mktemp(suffix=".sqlite")
            )
            rbase = f"http://127.0.0.1:{rport}"
            fleet = json.loads(get("/fleet/trace", at=rbase))
            fevs = fleet["traceEvents"]
            pids = {e["pid"] for e in fevs}
            assert pids == {1, 2}, pids  # one pid per daemon
            pnames = {
                e["pid"]: e["args"]["name"] for e in fevs
                if e["ph"] == "M" and e["name"] == "process_name"
            }
            assert len(pnames) == 2, pnames
            for pid in (1, 2):
                assert any(
                    e["pid"] == pid and e["name"] == "issue"
                    for e in fevs
                ), f"daemon pid {pid} contributed no issue span"
            # alignment: both daemons' events land on ONE clock —
            # non-negative, and spanning no more than this harness's
            # real lifetime (an unaligned epoch would be hours off)
            ts = [e["ts"] for e in fevs if "ts" in e]
            assert min(ts) >= 0 and max(ts) < 3600e6, (
                min(ts), max(ts)
            )
            # the trace id minted on daemon 1 filters the WHOLE
            # fleet's merged view down to that daemon's request
            ffilt = json.loads(
                get(f"/fleet/trace?trace_id={tid}", at=rbase)
            )
            fnm = [
                e for e in ffilt["traceEvents"] if e["ph"] != "M"
            ]
            assert fnm and all(e["pid"] == 1 for e in fnm), fnm
            ftext = get("/fleet/metrics", at=rbase).decode()
            fs, ft = parse_exposition(ftext)
            req_rows = fs["mlcomp_engine_requests_total"]
            assert len(req_rows) == 2, req_rows  # one per daemon label
            assert all("daemon=" in k for k in req_rows), req_rows
            ups = fs["mlcomp_fleet_daemon_up"]
            assert sorted(ups.values()) == [1.0, 1.0], ups

            # ---- the router end to end: a traced request lands in
            #      /fleet/trace under the REPLICA that served it
            def via_router(ids, headers=None):
                body = json.dumps(
                    {"prompt": ids, "max_new_tokens": 4}
                ).encode()
                req = urllib.request.Request(
                    f"{rrbase}/generate", data=body,
                    headers={"Content-Type": "application/json",
                             **(headers or {})},
                )
                with urllib.request.urlopen(req, timeout=600) as r:
                    return (
                        json.loads(r.read()),
                        r.headers.get("x-mlcomp-replica"),
                    )
            tid3 = "1bad5eed5eed5eed5eed5eed5eed5eed"
            out3, served_by = via_router(shared + [91], headers={
                "traceparent": f"00-{tid3}-00f067aa0ba902b7-01",
            })
            assert out3["trace_id"] == tid3, out3
            assert served_by in fleet_urls, served_by
            # the replica's daemon name -> its pid in the merged view
            daemon3 = fleet_urls[served_by].split("://", 1)[-1]
            served_pid = {v: k for k, v in pnames.items()}[daemon3]
            f3 = json.loads(
                get(f"/fleet/trace?trace_id={tid3}", at=rbase)
            )
            f3nm = [e for e in f3["traceEvents"] if e["ph"] != "M"]
            assert f3nm, "router-traced request left no fleet spans"
            assert all(e["pid"] == served_pid for e in f3nm), (
                served_pid, f3nm[:3],
            )

            # ---- affinity: the SAME prefix re-lands on the same
            #      replica and hits its warmed cache (cache-hit-token
            #      counters are the proof)
            p_aff = shared + [92]
            _, first_rep = via_router(p_aff)
            fleet_svcs[first_rep].prefix_cache.flush()
            out_rep, again_rep = via_router(p_aff)
            assert again_rep == first_rep, (first_rep, again_rep)
            assert out_rep.get("cache_hit_tokens", 0) > 0, out_rep
            rst = router.status()
            assert rst["counts"]["reason"]["affinity"] >= 1, rst

            # ---- the new metric families scrape clean from the
            #      router's shared fleet registry
            ftext2 = get("/metrics", at=rrbase).decode()
            fs2, ft2 = parse_exposition(ftext2)
            missing = [
                m for m in DOCUMENTED_FLEET_METRICS if m not in ft2
            ]
            assert not missing, f"fleet metrics absent: {missing}"
            assert fs2["mlcomp_fleet_replicas_live"][""] == 2, fs2
            ok_reqs = fs2["mlcomp_fleet_router_requests_total"][
                '{outcome="ok"}'
            ]
            assert ok_reqs >= 3, fs2["mlcomp_fleet_router_requests_total"]

            # ---- autoscaler: the decision log responds to an
            #      injected burn-rate breach (dry-run: logged and
            #      counted, target untouched)
            from mlcomp_tpu.fleet.autoscale import FleetSignals

            live_decision = scaler.run_tick(urls=list(
                fleet_urls.values()
            ))
            assert live_decision["signals"]["live_replicas"] == 2, (
                live_decision
            )
            breach = scaler.observe(FleetSignals(
                slo_breached=True, requests_delta=10, live_replicas=2,
            ))
            assert breach["direction"] == "up", breach
            assert breach["reason"] == "slo_burn", breach
            assert breach["dry_run"] and not breach["applied"], breach
            assert mgr.stats()["target"] == 2  # dry run never applies
            ftext3 = get("/metrics", at=rrbase).decode()
            fs3, _ = parse_exposition(ftext3)
            ups_dec = fs3["mlcomp_fleet_autoscale_decisions_total"][
                '{direction="up"}'
            ]
            assert ups_dec >= 1, fs3
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            if rhttpd is not None:
                rhttpd.shutdown()
                rhttpd.server_close()
            if router is not None:
                router.close()
            if mgr is not None:
                mgr.close(stop_replicas=False)
            if report_srv is not None:
                report_srv.shutdown()
                report_srv.server_close()
            httpd2.shutdown()
            httpd2.server_close()
            svc2.close()

        return {
            "requests": int(req1),
            "metric_families": len(t2),
            "trace_events": len(evs),
            "dispatch_spans": begins,
            "profile_dispatches": int(att["dispatches"]),
            "device_track_spans": len(dev_evs),
            "device_time_ms": att["device_time_ms"],
            "slo_evaluations": int(slo["evaluations"]),
            "history_samples": len(hist["samples"]),
            "trace_filter_events": len(non_meta),
            "fleet_daemons": len(pnames),
            "fleet_trace_events": len(fevs),
            "router_requests_ok": int(ok_reqs),
            "router_affinity_routes": int(
                rst["counts"]["reason"]["affinity"]
            ),
            "autoscale_decision": breach["direction"],
            "disagg_handoffs_imported": disagg_imports,
        }
    finally:
        httpd.shutdown()
        httpd.server_close()
        svc.close()


def main(argv=None) -> int:
    out = run()
    print(f"ok: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

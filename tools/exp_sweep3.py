"""Settle the ~1MB-block hypothesis on the remaining decode shapes:
lm_head at 1MB and down-proj (8192->2048) at 1/2/4MB."""
import statistics
import time

import jax
import jax.numpy as jnp

from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
from mlcomp_tpu.ops.quant import quantize_leaf

B, D, M = 8, 2048, 8192
key = jax.random.PRNGKey(0)


def qw(d_in, d_out, k):
    w = jax.random.normal(jax.random.fold_in(key, k), (d_in, d_out), jnp.float32)
    leaf = quantize_leaf(w)
    return leaf["q8"], leaf["q8_scale"].reshape(-1)


hd, hds = qw(D, 32768, 2)
dn, dns = qw(M, D, 6)

CASES = {
    "hd_n512_d2048": (hd, hds, D, 512, 2048),    # 1MB, 64 steps
    "hd_n1024_d2048": (hd, hds, D, 1024, 2048),  # 2MB, 32 steps
    "dn_n512_d2048": (dn, dns, M, 512, 2048),    # 1MB, 16 steps
    "dn_n512_d4096": (dn, dns, M, 512, 4096),    # 2MB, 8 steps
    "dn_n1024_d4096": (dn, dns, M, 1024, 4096),  # 4MB, 4 steps (today)
}
N_LO, N_HI = 128, 1536


def looped(spec, n):
    w, s, d_in, bn, bd = spec

    def f(x):
        y = quant_matmul(
            jnp.tile(x, (1, d_in // D)), w, s, block_n=bn, block_d=bd
        )
        return (y[:, :D] * 1e-3).astype(jnp.bfloat16)

    return jax.jit(lambda x: jax.lax.fori_loop(0, n, lambda i, h: f(h), x))


x0 = jax.random.normal(jax.random.fold_in(key, 99), (B, D), jnp.bfloat16)
fns = {}
for nm, spec in CASES.items():
    for n in (N_LO, N_HI):
        fns[(nm, n)] = looped(spec, n)
for kk, fn in fns.items():
    t0 = time.perf_counter()
    float(fn(x0)[0, 0])
    print(f"  {kk}: {time.perf_counter()-t0:.1f}s", flush=True)

times = {k: [] for k in fns}
for _ in range(7):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        float(fn(x0)[0, 0])
        times[kk].append(time.perf_counter() - t0)

for nm, spec in CASES.items():
    t_lo = statistics.median(times[(nm, N_LO)])
    t_hi = statistics.median(times[(nm, N_HI)])
    per = (t_hi - t_lo) / (N_HI - N_LO) * 1e6
    roof = spec[0].size / 819e9 * 1e6
    print(f"{nm:16s}: {per:8.2f} us/call  roofline {roof:6.1f} "
          f"({roof/per*100 if per>0 else 0:5.1f}%)")

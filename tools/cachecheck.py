#!/usr/bin/env python
"""Fault-injection harness for the prefix index (mlcomp_tpu/cache).

Randomizes the interleavings the serving engine produces — submit
(lookup + pin), retire (release), insert (with and without the
offset-dedup path), eviction pressure (budget shrink) — against
``PrefixIndex`` and asserts, after EVERY operation:

- structural invariants (``check_invariants``: byte accounting vs the
  stored blocks, edge labels, parent pointers);
- lookup correctness: the match is a prefix of the query, its segments
  reconstruct exactly the query's matched tokens, and — while the
  budget rules out eviction — its length equals the brute-force longest
  common prefix against every sequence ever inserted;
- ref-count pinning: data a lease holds stays byte-identical across
  interleaved inserts/splits/evictions until released, and releasing
  every lease returns the pinned-node count to zero;
- byte budget: once nothing is pinned, ``evict_to_budget`` always lands
  at or under ``max_bytes``.

Blocks are ``KVBlock``s whose single array IS the token ids — the same
slice bookkeeping the real KV rows ride, made self-checking.  No JAX
anywhere, so the harness runs in milliseconds; tests/test_cachecheck.py
wires a short run (plus a multi-threaded one — the concurrent-eviction
race) into tier-1.

Standalone fuzzing:

    python tools/cachecheck.py --iters 20000 --seed 3 --threads 4
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mlcomp_tpu.cache.kv_store import KVBlock  # noqa: E402
from mlcomp_tpu.cache.prefix_index import (  # noqa: E402
    PrefixIndex,
    _common_prefix_len as len_common,
)


def _block(ids) -> KVBlock:
    """A block whose payload is the ids themselves: any slice/split
    bookkeeping error shows up as a token mismatch at verify time."""
    arr = np.asarray(list(ids), np.int64)[None, :]
    return KVBlock({"ids": arr}, {"ids": 1}, len(ids))


def _lease_tokens(lease):
    out = []
    for block, take in lease.segments:
        out.extend(block.arrays["ids"][0, :take].tolist())
    return out


def _prompt(rng: random.Random, alphabet: int = 6, max_len: int = 24):
    """Prompts drawn from a small alphabet so shared prefixes (and
    therefore edge splits) are common, like real templated traffic."""
    n = rng.randint(1, max_len)
    return [rng.randrange(1, alphabet) for _ in range(n)]


def run(seed: int = 0, iters: int = 2000, max_bytes: int = 1 << 12,
        check_model: bool = False, index: PrefixIndex = None) -> dict:
    """One single-threaded fuzz run; returns op counts.  With
    ``check_model=True`` pass a budget large enough that nothing evicts
    — lookup lengths are then checked against a brute-force model."""
    rng = random.Random(seed)
    idx = index if index is not None else PrefixIndex(max_bytes)
    held = []          # (lease, expected_tokens) — simulated in-flight slots
    inserted = []      # every sequence ever inserted (brute-force model)
    ops = {"lookup": 0, "insert": 0, "offset_insert": 0, "release": 0,
           "evict": 0}

    def verify_lease(lease, expected):
        got = _lease_tokens(lease)
        assert got == expected, (got, expected)

    for _ in range(iters):
        op = rng.random()
        if op < 0.35:  # submit: lookup + pin
            ops["lookup"] += 1
            q = _prompt(rng)
            lease = idx.lookup(q)
            if lease is not None:
                assert 0 < lease.tokens <= len(q)
                expected = q[:lease.tokens]
                verify_lease(lease, expected)
                if check_model and inserted:
                    want = max(
                        len_common(q, s) for s in inserted
                    )
                    assert lease.tokens == want, (q, lease.tokens, want)
                if rng.random() < 0.7 and len(held) < 8:
                    held.append((lease, expected))
                else:
                    lease.release()
            elif check_model:
                assert not inserted or max(
                    len_common(q, s) for s in inserted
                ) == 0
        elif op < 0.6:  # insert a full prompt
            ops["insert"] += 1
            ids = _prompt(rng)
            idx.insert(ids, _block(ids))
            inserted.append(list(ids))
        elif op < 0.75:  # offset insert: the engine's dedup capture path
            ops["offset_insert"] += 1
            base = _prompt(rng) if not inserted else list(
                rng.choice(inserted)
            )
            ids = base + _prompt(rng, max_len=6)
            lease = idx.lookup(ids)
            off = 0 if lease is None else lease.tokens
            if lease is not None:
                lease.release()
            idx.insert(ids, _block(ids[off:]), offset=off)
            inserted.append(list(ids))
        elif op < 0.9 and held:  # retire: release a pinned lease
            ops["release"] += 1
            lease, expected = held.pop(rng.randrange(len(held)))
            # pinned data must have survived every interleaved
            # insert/split/eviction since the lookup
            verify_lease(lease, expected)
            lease.release()
        else:  # eviction pressure
            ops["evict"] += 1
            idx.evict_to_budget()
        idx.check_invariants()

    for lease, expected in held:
        verify_lease(lease, expected)
        lease.release()
    idx.check_invariants()
    if index is None:
        # global end-state checks only when this run OWNS the index
        # (under run_threaded, peers may still hold pins)
        stats = idx.stats()
        assert stats["pinned_nodes"] == 0, stats
        idx.evict_to_budget()
        assert idx.stats()["bytes"] <= max(idx.max_bytes, 0), idx.stats()
    return ops


def run_threaded(seed: int = 0, iters: int = 500, threads: int = 4,
                 max_bytes: int = 1 << 11) -> None:
    """The concurrent-eviction race: ``threads`` workers interleave
    submit/insert/retire/evict on ONE index under a tiny budget.
    Model checks are off (another thread's evictions are legal), but
    every structural/pinning/budget invariant must hold throughout."""
    idx = PrefixIndex(max_bytes)
    errs = []

    def worker(wseed):
        try:
            run(seed=wseed, iters=iters, max_bytes=max_bytes, index=idx)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [
        threading.Thread(target=worker, args=(seed * 1000 + i,))
        for i in range(threads)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]
    idx.check_invariants()
    assert idx.stats()["pinned_nodes"] == 0
    idx.evict_to_budget()
    assert idx.stats()["bytes"] <= max_bytes


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--iters", type=int, default=5000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--threads", type=int, default=0,
                   help="0 = single-threaded with brute-force model "
                   "checks; N>0 = N racing workers, tiny budget")
    p.add_argument("--max-bytes", type=int, default=1 << 12)
    args = p.parse_args(argv)
    if args.threads:
        run_threaded(seed=args.seed, iters=args.iters,
                     threads=args.threads, max_bytes=args.max_bytes)
        print(f"threaded ok: {args.threads} workers x {args.iters} ops")
    else:
        ops = run(seed=args.seed, iters=args.iters,
                  max_bytes=args.max_bytes)
        print(f"ok: {ops}")
        ops = run(seed=args.seed + 1, iters=args.iters,
                  max_bytes=1 << 30, check_model=True)
        print(f"model-checked ok: {ops}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""graftcheck: JAX-aware static analysis for the serving engine.

The engine's hardest invariants are runtime-invisible on the CPU-only
tier-1 container: a use-after-donate "works" on CPU and detonates on a
TPU (the donated buffer is really gone there), a lock-discipline slip
needs pod-scale concurrency to fire, and a tracer `bool()` only fails
once the offending branch actually traces.  This tool checks those
properties at the AST level — dependency-free (stdlib `ast` only, no
JAX import), whole-repo, in seconds — and rides tier-1 via
``tests/test_graftcheck.py``.

Four passes, stable rule ids:

==============  =====================================================
rule id         meaning
==============  =====================================================
use-after-donate  a local name / attribute passed in a DONATED
                  position of a jitted call is read again afterwards
                  without being rebound (the buffer no longer exists
                  on TPU; CPU aliases it and silently "works")
donation-vector   a function with a ``dstate`` parameter (the
                  engine's carry pytree) is jitted WITHOUT donating
                  that argument — carry programs must share one
                  donation story or the pipeline's in-place chain
                  breaks
donation-sharding a name that is DONATED in a function is also passed
                  to ``jax.device_put`` / ``with_sharding_constraint``
                  in that function — resharding a donated carry
                  between issue and reuse changes the buffer's
                  sharding out from under the donation chain (the
                  next call recompiles or silently copies instead of
                  aliasing); reshard at construction (the fresh
                  carry's jitted init), never mid-chain
host-sync         ``bool()/int()/float()``, ``.item()``, or a
                  ``np.*`` call on a traced value inside a
                  jit-reachable function (an implicit device sync,
                  or a trace error)
tracer-control-flow  Python ``if``/``while``/``assert`` on a traced
                  value inside a jit-reachable function
traced-time       ``time.time()``/``perf_counter()`` etc. inside a
                  jit-reachable function (traces to a constant)
unguarded-write   a write to a ``# guarded_by:`` annotated attribute
                  outside ``with <lock>:`` / outside a method
                  annotated for the owning thread domain
unguarded-read    same, for reads — only for annotations WITHOUT the
                  ``[writes]`` qualifier (writes-only mode is for
                  fields with a documented torn-read contract)
bad-annotation    a ``guarded_by``/``runs-on``/``holds`` annotation
                  that doesn't parse or doesn't attach to anything
metric-drift      metric families disagree between the code
                  collectors, the docs/observability.md catalog, and
                  tools/obs_check.py's enforced list
env-drift         an ``MLCOMP_*`` env var read (or set for a child
                  process) in code but missing from docs/serving.md's
                  environment table — or documented but unused
fault-drift       a fault point injected via utils/faults.py that no
                  chaos scenario or test ever arms (dead chaos
                  surface), or armed but never injected (stale test)
flag-drift        a ``--flag`` referenced in README/docs that no
                  ``add_argument`` in the repo defines
bad-suppression   a ``graftcheck: ignore`` comment without a reason
==============  =====================================================

Annotations (the lock-discipline vocabulary)::

    self._profile = None   # guarded_by: _prof_lock [writes]
    self._dstate = ...     # guarded_by: loop
    def _drain(self):      # graftcheck: runs-on(worker)
    def _evict(self):      # graftcheck: holds(_lock)

``guarded_by`` names either a lock attribute of the same class
(detected as a ``threading.Lock()/RLock()/Condition()`` assignment) or
a thread DOMAIN (``loop``, ``worker``, ``batcher`` — the single thread
entitled to the state; a watchdog-restart path that has proven the
loop dead may legitimately carry ``runs-on(loop)``).  ``[writes]``
enforces writes only — for fields with a documented torn-read
monitoring contract (the engine's ``_stats`` idiom).  Accesses in the
declaring class's ``__init__`` are always allowed (construction is
single-threaded).

Suppressions::

    self._stats["requests"] += 1  # graftcheck: ignore[unguarded-write] -- GIL-atomic; sole off-loop writer

The reason after ``--`` is mandatory; a bare ignore is itself a
finding.  A suppression on its own line applies to the next line.

CLI::

    python -m tools.graftcheck              # human output, exit 1 on findings
    python -m tools.graftcheck --json       # machine output
    python -m tools.graftcheck --rules use-after-donate,host-sync
    python -m tools.graftcheck --list-env   # dump the env/metric/fault
    python -m tools.graftcheck --list-metrics   # inventories the drift
    python -m tools.graftcheck --list-faults    # pass extracted from code

Scope and honesty: the donation and trace passes are heuristic — they
resolve what is statically resolvable (literal functions passed to
``jit``/``lax.scan``/``vmap``, the engine's ``self._fns`` getter
idiom) and say nothing about the rest.  docs/static_analysis.md
documents the exact approximations.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_RULES = (
    "use-after-donate", "donation-vector", "donation-sharding",
    "host-sync", "tracer-control-flow", "traced-time",
    "unguarded-write", "unguarded-read", "bad-annotation",
    "metric-drift", "env-drift", "fault-drift", "flag-drift",
    "bad-suppression",
)

# the seven files whose shared-state ownership story is annotated
LOCK_FILES = (
    "mlcomp_tpu/engine.py",
    "mlcomp_tpu/serve.py",
    "mlcomp_tpu/kvpool/pool.py",
    "mlcomp_tpu/kvpool/allocator.py",
    "mlcomp_tpu/cache/prefix_index.py",
    "mlcomp_tpu/cache/kv_store.py",
    "mlcomp_tpu/obs/metrics.py",
)

# metric families docs/observability.md documents as CONDITIONAL on a
# service configuration the tier-1 obs_check daemon does not run —
# they are exempt from the "docs ⊆ obs_check enforced list" direction
# (and only from that direction).  Keep each entry justified.
CONDITIONAL_METRICS = {
    # spec engines only (obs_check's daemon has no --engine-spec-k)
    "mlcomp_engine_spec_net_gain",
    "mlcomp_engine_spec_ineffective",
    # window/speculative batchers only (the daemon runs continuous)
    "mlcomp_service_requests_total",
    "mlcomp_service_queue_depth",
    # sharded engines only (the tier-1 obs_check daemon is mesh-less)
    "mlcomp_engine_mesh_devices",
    "mlcomp_engine_is_coordinator",
    # prefill replicas only (--phase prefill; the tier-1 obs_check
    # daemon is a paged decode-capable daemon — the EXPORT side's
    # counters are asserted by its dedicated disaggregation leg
    # against a prefill service's own scrape, not the enforced list)
    "mlcomp_engine_handoffs_exported_total",
    "mlcomp_engine_kv_pages_exported_total",
    "mlcomp_engine_handoff_bytes_exported_total",
}

MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse",
}

# attribute accesses that yield STATIC metadata, not a traced value
TAINT_BREAKERS = {"shape", "ndim", "dtype", "size", "itemsize"}

JNP_CALL_RE = re.compile(
    r"^(jnp|jax\.numpy|jax\.nn|jax\.lax|jax\.random|lax)\."
)

GUARD_RE = re.compile(
    r"#\s*guarded_by:\s*([A-Za-z_]\w*)\s*(\[writes\])?"
)
RUNS_RE = re.compile(r"#\s*graftcheck:\s*runs-on\((\w+)\)")
HOLDS_RE = re.compile(r"#\s*graftcheck:\s*holds\((\w+)\)")
IGNORE_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[([\w\-, ]+)\](\s*--\s*(\S.*))?"
)


class Finding:
    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message

    def key(self):
        return (self.path, self.line, self.rule, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "message": self.message}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleInfo:
    """One parsed file: tree, lines, parent links, suppressions."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # line -> set of suppressed rules ({"*"} = all)
        self.suppress: Dict[int, Set[str]] = {}
        self.bad_suppressions: List[int] = []
        self._fn_ann_cache: Dict[int, Tuple[Set[str], Set[str]]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = IGNORE_RE.search(line)
            if not m:
                continue
            if not m.group(3):
                self.bad_suppressions.append(i)
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            target = i
            if line.strip().startswith("#"):
                target = i + 1  # standalone comment covers the next line
            self.suppress.setdefault(target, set()).update(rules)
        if self.suppress:
            # a finding may anchor to ANY line of a multi-line
            # statement (the offending node's lineno), while the
            # suppression comment sits on the statement's last physical
            # line — widen each suppression to its whole statement
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt) or hasattr(node, "body"):
                    continue  # simple statements only: a compound
                    # stmt's span covers its whole body
                end = getattr(node, "end_lineno", None) or node.lineno
                if end == node.lineno:
                    continue
                for line_no in list(self.suppress):
                    if node.lineno <= line_no <= end:
                        rules = self.suppress[line_no]
                        for ln in range(node.lineno, end + 1):
                            self.suppress.setdefault(ln, set()).update(
                                rules
                            )

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def def_region_lines(self, fn: ast.AST) -> Iterable[str]:
        """The ``def`` line(s) up to (and including) the first body
        statement's line — where runs-on/holds annotations live."""
        first = fn.body[0].lineno if fn.body else fn.lineno
        lo = fn.lineno
        return self.lines[lo - 1:first]


def load_modules(root: str, rels: Sequence[str]) -> Dict[str, ModuleInfo]:
    out: Dict[str, ModuleInfo] = {}
    for rel in rels:
        path = os.path.join(root, rel)
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            out[rel] = ModuleInfo(path, rel, src)
        except (OSError, SyntaxError):
            continue
    return out


def python_files(root: str, subdirs: Sequence[str]) -> List[str]:
    rels: List[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base) and base.endswith(".py"):
            rels.append(sub)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rels.append(os.path.relpath(
                        os.path.join(dirpath, fn), root
                    ))
    return rels


# --------------------------------------------------------------- donation


def _donate_vector(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, int
                    ):
                        out.append(e.value)
                    else:
                        return None
                return tuple(out)
            return None
    return None


def _is_jit_call(call: ast.Call) -> bool:
    name = dotted(call.func)
    return bool(name) and (name == "jit" or name.endswith(".jit"))


def _local_defs(mi: ModuleInfo) -> Dict[ast.AST, Dict[str, ast.AST]]:
    """scope node -> {name: FunctionDef} for every def in the module
    (module, class, and function scopes)."""
    table: Dict[ast.AST, Dict[str, ast.AST]] = {}
    for node in ast.walk(mi.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = mi.parents.get(node)
            while scope is not None and not isinstance(
                scope, (ast.Module, ast.ClassDef, ast.FunctionDef,
                        ast.AsyncFunctionDef)
            ):
                scope = mi.parents.get(scope)
            table.setdefault(scope, {})[node.name] = node
    return table


def _resolve_name(mi: ModuleInfo, at: ast.AST, name: str,
                  defs: Dict[ast.AST, Dict[str, ast.AST]]):
    """Resolve ``name`` to a FunctionDef visible from ``at``."""
    scopes: List[ast.AST] = []
    cur: Optional[ast.AST] = at
    while cur is not None:
        if isinstance(cur, (ast.Module, ast.ClassDef, ast.FunctionDef,
                            ast.AsyncFunctionDef)):
            scopes.append(cur)
        cur = mi.parents.get(cur)
    for scope in scopes:
        hit = defs.get(scope, {}).get(name)
        if hit is not None:
            return hit
    return None


_COMPOUND_HEADERS = {
    ast.For: ("target", "iter"),
    ast.While: ("test",),
    ast.If: ("test",),
    ast.With: ("items",),
    ast.Try: (),
}


def _own_nodes(stmt: ast.stmt) -> List[ast.AST]:
    """The nodes that EXECUTE as part of this statement itself: for
    compound statements only the header expressions (their bodies are
    separate statements in the linear scan); nested function/class
    defs and lambdas are skipped (they run at call time)."""
    headers = _COMPOUND_HEADERS.get(type(stmt))
    roots: List[ast.AST]
    if headers is not None:
        roots = []
        for field in headers:
            v = getattr(stmt, field)
            roots.extend(v if isinstance(v, list) else [v])
    else:
        roots = [stmt]
    out: List[ast.AST] = []
    stack = list(roots)
    while stack:
        n = stack.pop()
        out.append(n)
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.Lambda, ast.FunctionDef,
                              ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            stack.append(c)
    return out


def _assign_targets_texts(stmt: ast.stmt) -> Set[str]:
    """Dotted texts this statement REBINDS (incl. tuple unpacking)."""
    out: Set[str] = set()

    def collect(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)
        else:
            txt = dotted(t)
            if txt:
                out.add(txt)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        collect(stmt.target)  # loop targets rebind too
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    # walrus targets anywhere in the statement's own expressions
    for node in _own_nodes(stmt):
        if isinstance(node, ast.NamedExpr):
            collect(node.target)
    return out


class _DonationGetters(ast.NodeVisitor):
    """Engine idiom: a method whose body jits-with-donation into
    ``self._fns[...]`` is a donating GETTER — ``self.method(...)(...)``
    call sites inherit its donation vector."""

    def __init__(self):
        self.getters: Dict[str, Tuple[int, ...]] = {}

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _is_jit_call(sub):
                vec = _donate_vector(sub)
                if vec:
                    self.getters[node.name] = vec
                    break
        self.generic_visit(node)


def check_donation(mi: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    defs = _local_defs(mi)

    # 1) carry-consistency: a literal function with a `dstate` param
    #    jitted without donating that position
    for node in ast.walk(mi.tree):
        if not (isinstance(node, ast.Call) and _is_jit_call(node)):
            continue
        if not node.args:
            continue
        target = node.args[0]
        fn = None
        if isinstance(target, ast.Name):
            fn = _resolve_name(mi, node, target.id, defs)
        if fn is None:
            continue
        params = [a.arg for a in fn.args.args]
        vec = _donate_vector(node) or ()
        if "dstate" in params:
            idx = params.index("dstate")
            if idx not in vec:
                findings.append(Finding(
                    "donation-vector", mi.rel, node.lineno,
                    f"'{fn.name}' consumes the engine carry (param "
                    f"'dstate' at position {idx}) but the jit donates "
                    f"{vec or 'nothing'} — carry programs must donate "
                    "the carry or the in-place dispatch chain breaks",
                ))

    # 2) collect donating callables reachable from call sites
    getters = _DonationGetters()
    getters.visit(mi.tree)
    # function-scope -> {name: vector} for `var = jax.jit(f, donate…)`
    jit_vars: Dict[Optional[int], Dict[str, Tuple[int, ...]]] = {}
    for node in ast.walk(mi.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _is_jit_call(node.value)):
            vec = _donate_vector(node.value)
            if vec:
                fns = mi.enclosing_functions(node)
                key = id(fns[0]) if fns else None
                jit_vars.setdefault(key, {})[node.targets[0].id] = vec

    def call_vector(call: ast.Call,
                    scope_ids: List[Optional[int]]
                    ) -> Optional[Tuple[int, ...]]:
        # `var(...)` where var = jax.jit(f, donate_argnums=...)
        if isinstance(call.func, ast.Name):
            for key in scope_ids:
                vec = jit_vars.get(key, {}).get(call.func.id)
                if vec:
                    return vec
            return None
        # `self._insert_fn()(...)` / `self._fused_dispatch_fn(c)(...)`
        if isinstance(call.func, ast.Call):
            inner = call.func.func
            if isinstance(inner, ast.Attribute):
                return getters.getters.get(inner.attr)
        return None

    # 3) use-after-donate: linear scan of each function body
    for fn in ast.walk(mi.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scope_ids: List[Optional[int]] = [id(fn)] + [
            id(f) for f in mi.enclosing_functions(fn)
        ] + [None]
        stmts: List[ast.stmt] = []

        def flatten(body: List[ast.stmt]) -> None:
            for s in body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue  # runs at call time, not here
                stmts.append(s)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(s, field, None)
                    if sub:
                        flatten(sub)
                for h in getattr(s, "handlers", []) or []:
                    flatten(h.body)

        flatten(fn.body)
        stmts.sort(key=lambda s: s.lineno)
        tainted: Dict[str, int] = {}  # expr text -> donating call line
        donated_any: Dict[str, int] = {}  # name -> first donation line
        reshards: List[Tuple[str, int, str]] = []  # (name, line, fn)
        for stmt in stmts:
            nodes = _own_nodes(stmt)
            rebound = _assign_targets_texts(stmt)
            # reads of donated-dead values in this statement
            if tainted:
                for node in nodes:
                    if isinstance(node, (ast.Name, ast.Attribute)) and (
                        isinstance(getattr(node, "ctx", None), ast.Load)
                    ):
                        txt = dotted(node)
                        if txt in tainted:
                            findings.append(Finding(
                                "use-after-donate", mi.rel, node.lineno,
                                f"'{txt}' was donated to the jitted "
                                f"call at line {tainted[txt]} and is "
                                "read again here — the buffer no "
                                "longer exists on TPU (CPU aliases it "
                                "and silently 'works')",
                            ))
                            del tainted[txt]
            for txt in rebound:
                tainted.pop(txt, None)
            # new donations from this statement
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func) or ""
                leaf = name.split(".")[-1]
                if leaf in ("device_put", "with_sharding_constraint"
                            ) and node.args:
                    txt = dotted(node.args[0])
                    if txt is not None:
                        reshards.append((txt, node.lineno, leaf))
                vec = call_vector(node, scope_ids)
                if not vec:
                    continue
                for idx in vec:
                    if idx >= len(node.args):
                        continue
                    txt = dotted(node.args[idx])
                    if txt is None:
                        continue
                    donated_any.setdefault(txt, node.lineno)
                    if txt in rebound:
                        continue  # the same stmt rebinds it (the idiom)
                    tainted[txt] = node.lineno
        # donation-sharding: the same function both DONATES a name and
        # reshards it (device_put / with_sharding_constraint) — the
        # donated chain's buffer sharding changes between issue and
        # reuse, so the next donating call recompiles or copies
        # instead of aliasing.  Deliberately order-insensitive: loop
        # bodies donate and reuse across iterations, so a reshard
        # "before" the donation in source order still hits the chain
        # (a genuine construct-then-donate sequence in one function is
        # rare — suppress with a reason).
        for txt, line, how in reshards:
            if txt in donated_any:
                findings.append(Finding(
                    "donation-sharding", mi.rel, line,
                    f"'{txt}' is donated in this function (line "
                    f"{donated_any[txt]}) and resharded here by "
                    f"{how} — donation vectors must preserve "
                    "shardings: reshard at construction (the fresh "
                    "carry's jitted init with out_shardings), never "
                    "between issue and reuse",
                ))
    return findings


# ------------------------------------------------------------ trace pass

TIME_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "datetime.datetime.now", "datetime.now",
}

TRACED_SEED_SUFFIXES = (".jit", "lax.scan", ".vmap", "lax.cond",
                        "lax.while_loop", "lax.fori_loop")


def _seed_traced(mi: ModuleInfo, defs) -> List[ast.AST]:
    """Function nodes syntactically passed to jit / scan / vmap /
    cond / while_loop / fori_loop (Name or Lambda args)."""
    roots: List[ast.AST] = []
    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        if not (name == "jit" or name == "vmap" or name == "scan"
                or any(name.endswith(s) for s in TRACED_SEED_SUFFIXES)):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Lambda):
                roots.append(arg)
            elif isinstance(arg, ast.Name):
                fn = _resolve_name(mi, node, arg.id, defs)
                if fn is not None:
                    roots.append(fn)
    return roots


def _expand_traced(mi: ModuleInfo, roots: List[ast.AST], defs
                   ) -> List[ast.AST]:
    """Follow same-module calls (plain names and self-methods) from
    the seeds, depth-bounded."""
    seen: Set[int] = set()
    out: List[ast.AST] = []
    frontier = [(r, 0) for r in roots]
    while frontier:
        fn, depth = frontier.pop()
        if id(fn) in seen or depth > 3:
            continue
        seen.add(id(fn))
        out.append(fn)
        cls = mi.enclosing_class(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = _resolve_name(mi, fn, node.func.id, defs)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "self" and cls is not None):
                callee = defs.get(cls, {}).get(node.func.attr)
            if callee is not None:
                frontier.append((callee, depth + 1))
    return out


def _expr_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Does the expression reference a traced value?  Attribute access
    of static metadata (``.shape`` etc.) and ``len()`` break taint;
    results of arbitrary (non-jnp) calls are NOT considered traced."""
    if isinstance(node, ast.Attribute):
        if node.attr in TAINT_BREAKERS:
            return False
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Subscript):
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name and JNP_CALL_RE.match(name):
            return True
        if name == "len":
            return False
        return False  # opaque call: assume host value (documented)
    if isinstance(node, (ast.BoolOp,)):
        return any(_expr_tainted(v, tainted) for v in node.values)
    if isinstance(node, ast.BinOp):
        return _expr_tainted(node.left, tainted) or _expr_tainted(
            node.right, tainted
        )
    if isinstance(node, ast.UnaryOp):
        return _expr_tainted(node.operand, tainted)
    if isinstance(node, ast.Compare):
        return _expr_tainted(node.left, tainted) or any(
            _expr_tainted(c, tainted) for c in node.comparators
        )
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_expr_tainted(e, tainted) for e in node.elts)
    if isinstance(node, ast.IfExp):
        return any(_expr_tainted(n, tainted)
                   for n in (node.test, node.body, node.orelse))
    return False


def check_traced_fn(mi: ModuleInfo, fn: ast.AST) -> List[Finding]:
    findings: List[Finding] = []
    if isinstance(fn, ast.Lambda):
        body_nodes = list(ast.walk(fn.body))
    else:
        body_nodes = [n for s in fn.body for n in ast.walk(s)]
    # Taint = values provably traced: results of jnp/jax.lax/jax.nn/
    # jax.random calls (+ arithmetic over them).  Parameters are NOT
    # tainted: the repo's traced functions routinely take static
    # Python knobs (top_k, causal, chunk widths) as plain params, and
    # flagging every `if knob:` would bury the real hazards.  The
    # price (documented in docs/static_analysis.md): a hazard on a
    # parameter used directly is missed unless it first flows through
    # a jnp op.
    tainted: Set[str] = set()
    # one forward sweep: direct assignments from jnp/jax calls or
    # tainted expressions taint their targets
    for node in body_nodes:
        if isinstance(node, ast.Assign) and _expr_tainted(
            node.value, tainted
        ):
            for txt in _assign_targets_texts(node):
                if "." not in txt:
                    tainted.add(txt)
    for node in body_nodes:
        # nested defs are analyzed on their own (reachability)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, (ast.If, ast.While)):
            if _expr_tainted(node.test, tainted):
                findings.append(Finding(
                    "tracer-control-flow", mi.rel, node.lineno,
                    "Python control flow on a traced value inside a "
                    "jit-reachable function — use lax.cond/select "
                    "(this either fails to trace or bakes in one "
                    "branch)",
                ))
        elif isinstance(node, ast.Assert):
            if _expr_tainted(node.test, tainted):
                findings.append(Finding(
                    "tracer-control-flow", mi.rel, node.lineno,
                    "assert on a traced value inside a jit-reachable "
                    "function (TracerBoolConversionError at trace "
                    "time)",
                ))
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in TIME_CALLS:
                findings.append(Finding(
                    "traced-time", mi.rel, node.lineno,
                    f"{name}() inside a jit-reachable function traces "
                    "to a constant — hoist it to the host boundary",
                ))
            elif name in ("bool", "int", "float") and node.args and not (
                isinstance(node.args[0], ast.Constant)
            ) and _expr_tainted(node.args[0], tainted):
                findings.append(Finding(
                    "host-sync", mi.rel, node.lineno,
                    f"{name}() on a traced value — an implicit host "
                    "sync (or TracerBoolConversionError); keep it on "
                    "device or fetch explicitly at the boundary",
                ))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args):
                findings.append(Finding(
                    "host-sync", mi.rel, node.lineno,
                    ".item() inside a jit-reachable function — an "
                    "implicit device sync (TracerError under jit)",
                ))
            elif name and (name.startswith("np.")
                           or name.startswith("numpy.")) and any(
                _expr_tainted(a, tainted) for a in node.args
            ):
                findings.append(Finding(
                    "host-sync", mi.rel, node.lineno,
                    f"{name}() on a traced value — numpy forces a "
                    "device sync / concrete value inside a trace; use "
                    "jnp or move it to the host boundary",
                ))
    return findings


def check_trace(mi: ModuleInfo) -> List[Finding]:
    defs = _local_defs(mi)
    roots = _seed_traced(mi, defs)
    findings: List[Finding] = []
    for fn in _expand_traced(mi, roots, defs):
        findings.extend(check_traced_fn(mi, fn))
    return findings


# ------------------------------------------------------------- lock pass


class _GuardInfo:
    __slots__ = ("cls", "attr", "guard", "writes_only", "line")

    def __init__(self, cls, attr, guard, writes_only, line):
        self.cls = cls
        self.attr = attr
        self.guard = guard
        self.writes_only = writes_only
        self.line = line


def _collect_lock_attrs(mi: ModuleInfo, cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            name = dotted(node.value.func) or ""
            if name.split(".")[-1] in ("Lock", "RLock", "Condition"):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
    return out


def _fn_annotations(mi: ModuleInfo, fn: ast.AST) -> Tuple[Set[str],
                                                          Set[str]]:
    cached = mi._fn_ann_cache.get(id(fn))
    if cached is not None:
        return cached
    runs: Set[str] = set()
    holds: Set[str] = set()
    for line in mi.def_region_lines(fn):
        for m in RUNS_RE.finditer(line):
            runs.add(m.group(1))
        for m in HOLDS_RE.finditer(line):
            holds.add(m.group(1))
    mi._fn_ann_cache[id(fn)] = (runs, holds)
    return runs, holds


def _is_write_access(mi: ModuleInfo, node: ast.Attribute) -> bool:
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    parent = mi.parents.get(node)
    # self.x[...] = / self.x[...] += : the Subscript carries Store
    if isinstance(parent, ast.Subscript) and parent.value is node and (
        isinstance(parent.ctx, (ast.Store, ast.Del))
    ):
        return True
    # slice-assign targets: self.x[:] = ...
    if isinstance(parent, ast.Subscript) and parent.value is node:
        gp = mi.parents.get(parent)
        if isinstance(gp, ast.AugAssign) and gp.target is parent:
            return True
    # mutator method call: self.x.append(...)
    if (isinstance(parent, ast.Attribute)
            and parent.value is node
            and parent.attr in MUTATOR_METHODS):
        gp = mi.parents.get(parent)
        if isinstance(gp, ast.Call) and gp.func is parent:
            return True
    # aug-assign directly on the attribute: self.x += 1
    if isinstance(parent, ast.AugAssign) and parent.target is node:
        return True
    return False


def _under_lock(mi: ModuleInfo, node: ast.AST, recv: str,
                guard: str) -> bool:
    cur = mi.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                txt = dotted(item.context_expr)
                # receiver-matched ONLY: `with self._lock:` guards
                # self.X, `with index._lock:` guards index.X.  A bare
                # `with _lock:` (or an alias) is NOT accepted — a
                # same-named but different lock must not certify the
                # access; write the explicit form.
                if txt == f"{recv}.{guard}":
                    return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _, holds = _fn_annotations(mi, cur)
            if guard in holds:
                return True
        cur = mi.parents.get(cur)
    return False


def check_locks(mods: Dict[str, ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    guards: List[_GuardInfo] = []
    lock_attrs: Dict[Tuple[str, str], Set[str]] = {}

    # collect annotations
    for rel, mi in mods.items():
        for cls in [n for n in ast.walk(mi.tree)
                    if isinstance(n, ast.ClassDef)]:
            lock_attrs[(rel, cls.name)] = _collect_lock_attrs(mi, cls)
        for i, line in enumerate(mi.lines, start=1):
            m = GUARD_RE.search(line)
            if not m:
                continue
            guard, writes_only = m.group(1), bool(m.group(2))
            # attach to a `self.X = ...` on this line
            attached = False
            for node in ast.walk(mi.tree):
                if isinstance(node, (ast.Assign, ast.AnnAssign)) and (
                    node.lineno <= i <= (node.end_lineno or node.lineno)
                ):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            cls = mi.enclosing_class(node)
                            if cls is None:
                                continue
                            guards.append(_GuardInfo(
                                (rel, cls.name), t.attr, guard,
                                writes_only, i,
                            ))
                            attached = True
            if not attached:
                findings.append(Finding(
                    "bad-annotation", rel, i,
                    "guarded_by annotation does not attach to a "
                    "`self.<attr> = ...` assignment on this line",
                ))

    by_class: Dict[Tuple[str, str], Dict[str, _GuardInfo]] = {}
    by_attr: Dict[str, List[_GuardInfo]] = {}
    for g in guards:
        by_class.setdefault(g.cls, {})[g.attr] = g
        by_attr.setdefault(g.attr, []).append(g)

    # enforce
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Attribute):
                continue
            recv = dotted(node.value)
            if recv is None:
                continue
            g: Optional[_GuardInfo] = None
            encl_cls = mi.enclosing_class(node)
            if recv == "self" and encl_cls is not None:
                g = by_class.get((rel, encl_cls.name), {}).get(node.attr)
            if g is None:
                cands = by_attr.get(node.attr, [])
                if recv != "self" and len(cands) == 1:
                    g = cands[0]
                elif recv != "self" and len({c.guard for c in cands}) > 1:
                    continue  # ambiguous foreign access: skip
                elif recv != "self" and len(cands) > 1:
                    g = cands[0]
            if g is None:
                continue
            is_write = _is_write_access(mi, node)
            if g.writes_only and not is_write:
                continue
            fns = mi.enclosing_functions(node)
            if fns and fns[-1].name == "__init__" and recv == "self" and (
                encl_cls is not None and (rel, encl_cls.name) == g.cls
            ):
                continue  # construction is single-threaded
            decl_rel, decl_cls = g.cls
            locks = lock_attrs.get(g.cls, set())
            ok = False
            if g.guard in locks or g.guard.endswith("lock"):
                ok = _under_lock(mi, node, recv, g.guard)
            else:  # thread-domain guard
                for fn in fns:
                    runs, _ = _fn_annotations(mi, fn)
                    if g.guard in runs:
                        ok = True
                        break
            if ok:
                continue
            rule = "unguarded-write" if is_write else "unguarded-read"
            kind = "write to" if is_write else "read of"
            where = (
                f"`with {g.guard}:`" if (g.guard in locks
                                         or g.guard.endswith("lock"))
                else f"a method annotated runs-on({g.guard})"
            )
            findings.append(Finding(
                rule, rel, node.lineno,
                f"{kind} '{recv}.{node.attr}' (guarded_by: {g.guard}"
                f"{' [writes]' if g.writes_only else ''}, declared "
                f"{decl_rel}:{g.line} in {decl_cls}) outside {where}",
            ))
    return findings


# ------------------------------------------------------------ drift pass


ENV_KEY_RE = re.compile(r"^(MLCOMP_\w+|BENCH_TIER)$")


def collect_env_vars(mods: Dict[str, ModuleInfo]
                     ) -> Dict[str, List[Tuple[str, int, str]]]:
    """env name -> [(rel, line, 'read'|'set')] across the code set."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}

    def record(name: str, rel: str, line: int, kind: str) -> None:
        if ENV_KEY_RE.match(name):
            out.setdefault(name, []).append((rel, line, kind))

    for rel, mi in mods.items():
        if rel == "tools/graftcheck.py":
            continue  # this tool's own rule strings are not env reads
        for node in ast.walk(mi.tree):
            # os.environ.get("X", ...) / os.getenv("X") — plus any
            # helper taking the env NAME as its first argument (the
            # bench's _block_on("MLCOMP_BENCH_SKIP_...") idiom)
            if isinstance(node, ast.Call):
                if node.args and isinstance(
                    node.args[0], ast.Constant
                ) and isinstance(node.args[0].value, str) and (
                    ENV_KEY_RE.match(node.args[0].value)
                ):
                    record(node.args[0].value, rel, node.lineno, "read")
            # environ["X"] loads, env["X"] = ... stores
            if isinstance(node, ast.Subscript) and isinstance(
                node.slice, ast.Constant
            ) and isinstance(node.slice.value, str):
                base = dotted(node.value) or ""
                key = node.slice.value
                if isinstance(node.ctx, ast.Load) and base.endswith(
                    "environ"
                ):
                    record(key, rel, node.lineno, "read")
                elif isinstance(node.ctx, ast.Store):
                    record(key, rel, node.lineno, "set")
            # "X" in os.environ
            if isinstance(node, ast.Compare) and isinstance(
                node.left, ast.Constant
            ) and isinstance(node.left.value, str) and any(
                isinstance(op, (ast.In, ast.NotIn))
                for op in node.ops
            ):
                for comp in node.comparators:
                    if (dotted(comp) or "").endswith("environ"):
                        record(node.left.value, rel, node.lineno, "read")
    return out


def parse_md_section(md: str, heading: str) -> str:
    lines = md.splitlines()
    out: List[str] = []
    active = False
    for line in lines:
        if line.startswith("## "):
            active = line.strip() == heading
            continue
        if active:
            out.append(line)
    return "\n".join(out)


BACKTICK_RE = re.compile(r"`([^`]+)`")


def parse_env_table(serving_md: str) -> Set[str]:
    sec = parse_md_section(serving_md, "## Environment variables")
    out: Set[str] = set()
    for line in sec.splitlines():
        if not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if not cells:
            continue
        m = BACKTICK_RE.search(cells[0])
        if m and ENV_KEY_RE.match(m.group(1)):
            out.add(m.group(1))
    return out


def parse_metric_docs(obs_md: str,
                      heading: str = "## Metrics catalog — serve daemon"
                      ) -> Set[str]:
    sec = parse_md_section(obs_md, heading)
    out: Set[str] = set()
    for line in sec.splitlines():
        if not line.startswith("|"):
            continue
        name_cell = line.strip("|").split("|")[0]
        for tok in BACKTICK_RE.findall(name_cell):
            tok = re.sub(r"\{[^}]*=[^}]*\}", "", tok)  # label suffix
            m = re.match(r"^([a-z0-9_]*)\{([a-z0-9_,]+)\}([a-z0-9_]*)$",
                         tok)
            if m:  # brace expansion: prefix{a,b,c}suffix
                for mid in m.group(2).split(","):
                    name = m.group(1) + mid + m.group(3)
                    if name.startswith("mlcomp_"):
                        out.add(name)
                continue
            if re.match(r"^mlcomp_[a-z0-9_]+$", tok):
                out.add(tok)
    return out


METRIC_FN_NAMES = {"counter", "gauge", "histogram", "ctr", "gau"}


def collect_code_metrics(mods: Dict[str, ModuleInfo]
                         ) -> Dict[str, Tuple[str, int]]:
    """metric name (or glob 'prefix*suffix' for f-strings) ->
    (rel, line), from first args of counter/gauge/histogram calls."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fname = (dotted(node.func) or "").split(".")[-1]
            if fname not in METRIC_FN_NAMES:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ) and arg.value.startswith("mlcomp_"):
                out.setdefault(arg.value, (rel, node.lineno))
            elif isinstance(arg, ast.JoinedStr):
                parts: List[str] = []
                for v in arg.values:
                    if isinstance(v, ast.Constant):
                        parts.append(v.value)
                    else:
                        parts.append("*")
                pat = "".join(parts)
                if pat.startswith("mlcomp_"):
                    out.setdefault(pat, (rel, node.lineno))
    return out


def _glob_match(pattern: str, name: str) -> bool:
    return re.fullmatch(
        ".*".join(re.escape(p) for p in pattern.split("*")), name
    ) is not None


def parse_obs_check_list(mi: ModuleInfo,
                         list_name: str = "DOCUMENTED_SERVE_METRICS"
                         ) -> Tuple[Set[str], int]:
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == list_name
            for t in node.targets
        ) and isinstance(node.value, (ast.List, ast.Tuple)):
            names = {
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, str
                )
            }
            return names, node.lineno
    return set(), 0


def collect_fault_points(mods: Dict[str, ModuleInfo]
                         ) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                fname = (dotted(node.func) or "").split(".")[-1]
                if fname in ("inject", "_inject_fault") and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Constant) and isinstance(
                        a.value, str
                    ):
                        out.setdefault(a.value, (rel, node.lineno))
    return out


def collect_armed_points(mods: Dict[str, ModuleInfo]) -> Set[str]:
    out: Set[str] = set()
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                fname = (dotted(node.func) or "").split(".")[-1]
                if fname == "arm" and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Constant) and isinstance(
                        a.value, str
                    ):
                        out.add(a.value)
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ) and ":" in node.value:
                # MLCOMP_FAULTS-style spec strings ("point:kill:1")
                for item in node.value.split(","):
                    parts = item.split(":")
                    if len(parts) >= 2 and parts[1].startswith(
                        ("raise", "kill", "sleep")
                    ):
                        out.add(parts[0].strip())
    return out


FLAG_RE = re.compile(r"`[^`]*?(--[a-z][a-z0-9-]+)")


def collect_cli_flags(mods: Dict[str, ModuleInfo]) -> Set[str]:
    out: Set[str] = set()
    for rel, mi in mods.items():
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                fname = (dotted(node.func) or "").split(".")[-1]
                if fname == "add_argument":
                    for a in node.args:
                        if isinstance(a, ast.Constant) and isinstance(
                            a.value, str
                        ) and a.value.startswith("--"):
                            out.add(a.value)
    return out


def check_drift(root: str,
                mods: Optional[Dict[str, ModuleInfo]] = None
                ) -> List[Finding]:
    """``mods`` (rel -> ModuleInfo for mlcomp_tpu/bench.py/tools) lets
    run_passes share its parse; standalone calls re-parse."""
    findings: List[Finding] = []
    if mods is None:
        mods = load_modules(root, python_files(
            root, ("mlcomp_tpu", "bench.py", "tools")
        ))
    code = {
        rel: mi for rel, mi in mods.items()
        if not rel.startswith("tools/")
    }
    tools_mods = {
        rel: mi for rel, mi in mods.items() if rel.startswith("tools/")
    }
    tests_mods = load_modules(root, python_files(root, ("tests",)))

    def read(rel: str) -> str:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""

    serving_md = read("docs/serving.md")
    obs_md = read("docs/observability.md")

    # ---- env vars: code set vs the serving.md table.  The driver
    # entry (__graft_entry__.py) reads bench-style skip envs for its
    # dryrun blocks — part of the env contract, scanned here only
    # (its donation/trace story is the dryruns' own)
    entry_mods = load_modules(root, ["__graft_entry__.py"])
    env_code = collect_env_vars({**code, **tools_mods, **entry_mods})
    env_docs = parse_env_table(serving_md)
    if "## Environment variables" not in serving_md:
        findings.append(Finding(
            "env-drift", "docs/serving.md", 1,
            "no '## Environment variables' table found — the env-var "
            "contract is undocumented",
        ))
    for name, sites in sorted(env_code.items()):
        if name not in env_docs:
            rel, line, kind = sites[0]
            findings.append(Finding(
                "env-drift", rel, line,
                f"env var {name} is {kind} here but missing from "
                "docs/serving.md's '## Environment variables' table",
            ))
    for name in sorted(env_docs - set(env_code)):
        findings.append(Finding(
            "env-drift", "docs/serving.md", 1,
            f"env var {name} is documented but never read or set in "
            "mlcomp_tpu/, tools/, or bench.py — stale row",
        ))

    # ---- metrics: collectors vs docs catalog vs obs_check list
    metric_mods = {
        rel: mi for rel, mi in code.items()
        if rel in ("mlcomp_tpu/engine.py", "mlcomp_tpu/serve.py")
        or rel.startswith("mlcomp_tpu/obs/")
    }
    code_metrics = collect_code_metrics(metric_mods)
    docs_metrics = parse_metric_docs(obs_md)
    obs_mi = tools_mods.get("tools/obs_check.py")
    enforced, enforced_line = (
        parse_obs_check_list(obs_mi) if obs_mi else (set(), 0)
    )
    internal = {"mlcomp_metrics_collector_errors_total"}
    for name, (rel, line) in sorted(code_metrics.items()):
        if name in internal:
            continue
        if "*" in name:
            if not any(_glob_match(name, d) for d in docs_metrics):
                findings.append(Finding(
                    "metric-drift", rel, line,
                    f"metric family pattern {name!r} registered here "
                    "matches nothing in docs/observability.md's serve-"
                    "daemon catalog",
                ))
        elif name not in docs_metrics:
            findings.append(Finding(
                "metric-drift", rel, line,
                f"metric {name} registered here is missing from "
                "docs/observability.md's serve-daemon catalog",
            ))
    patterns = [n for n in code_metrics if "*" in n]
    for name in sorted(docs_metrics):
        if name in code_metrics:
            continue
        if any(_glob_match(p, name) for p in patterns):
            continue
        findings.append(Finding(
            "metric-drift", "docs/observability.md", 1,
            f"documented serve-daemon metric {name} is registered by "
            "no collector in engine.py/serve.py/obs/ — stale row",
        ))
    for name in sorted(enforced - docs_metrics):
        findings.append(Finding(
            "metric-drift", "tools/obs_check.py", enforced_line,
            f"obs_check enforces {name} but docs/observability.md's "
            "serve-daemon catalog does not document it",
        ))
    for name in sorted(docs_metrics - enforced - CONDITIONAL_METRICS):
        findings.append(Finding(
            "metric-drift", "tools/obs_check.py", enforced_line or 1,
            f"documented metric {name} is missing from obs_check's "
            "DOCUMENTED_SERVE_METRICS enforcement list (conditional "
            "families belong in graftcheck's CONDITIONAL_METRICS with "
            "a justification)",
        ))

    # ---- fleet control-plane metrics: the same three-way sync for
    # mlcomp_tpu/fleet/ collectors vs the fleet docs catalog vs
    # obs_check's DOCUMENTED_FLEET_METRICS list (the fleet surfaces
    # scrape from the ROUTER's /metrics, not the serve daemon's, so
    # they get their own catalog section and enforcement list)
    fleet_mods = {
        rel: mi for rel, mi in code.items()
        if rel.startswith("mlcomp_tpu/fleet/")
    }
    fleet_code = collect_code_metrics(fleet_mods)
    fleet_docs = parse_metric_docs(
        obs_md, heading="## Metrics catalog — fleet control plane"
    )
    fleet_enforced, fleet_line = (
        parse_obs_check_list(obs_mi, "DOCUMENTED_FLEET_METRICS")
        if obs_mi else (set(), 0)
    )
    for name, (rel, line) in sorted(fleet_code.items()):
        if name not in fleet_docs:
            findings.append(Finding(
                "metric-drift", rel, line,
                f"fleet metric {name} registered here is missing from "
                "docs/observability.md's fleet control-plane catalog",
            ))
    for name in sorted(fleet_docs - set(fleet_code)):
        findings.append(Finding(
            "metric-drift", "docs/observability.md", 1,
            f"documented fleet metric {name} is registered by no "
            "collector in mlcomp_tpu/fleet/ — stale row",
        ))
    for name in sorted(fleet_enforced - fleet_docs):
        findings.append(Finding(
            "metric-drift", "tools/obs_check.py", fleet_line,
            f"obs_check enforces fleet metric {name} but "
            "docs/observability.md's fleet catalog does not document "
            "it",
        ))
    for name in sorted(fleet_docs - fleet_enforced):
        findings.append(Finding(
            "metric-drift", "tools/obs_check.py", fleet_line or 1,
            f"documented fleet metric {name} is missing from "
            "obs_check's DOCUMENTED_FLEET_METRICS enforcement list",
        ))

    # ---- fault points vs the chaos/test surface that drives them
    points = collect_fault_points(code)
    armed = collect_armed_points({**tools_mods, **tests_mods})
    for point, (rel, line) in sorted(points.items()):
        if point not in armed:
            findings.append(Finding(
                "fault-drift", rel, line,
                f"fault point {point!r} is injected here but no chaos "
                "scenario (tools/chaoscheck.py) or test ever arms it "
                "— dead chaos surface",
            ))

    # ---- doc-referenced CLI flags must exist
    defined = collect_cli_flags({**code, **tools_mods})
    doc_files = ["README.md", "docs/serving.md", "docs/observability.md",
                 "docs/prefix_cache.md", "docs/static_analysis.md"]
    for rel in doc_files:
        text = read(rel)
        for i, line in enumerate(text.splitlines(), start=1):
            for m in FLAG_RE.finditer(line):
                flag = m.group(1)
                # docs spell some flags with their value glued on
                base = flag.split("=")[0]
                if base in defined:
                    continue
                if any(d.startswith(base) for d in defined):
                    continue
                findings.append(Finding(
                    "flag-drift", rel, i,
                    f"doc references CLI flag {base!r} but no "
                    "add_argument in mlcomp_tpu/ or tools/ defines it",
                ))
    return findings


# ---------------------------------------------------------------- driver


def run_passes(root: str = REPO,
               rules: Optional[Set[str]] = None) -> List[Finding]:
    rules = rules or set(ALL_RULES)
    findings: List[Finding] = []
    code_rels = python_files(
        root, ("mlcomp_tpu", "bench.py")
    ) + python_files(root, ("tools",))
    mods = load_modules(root, code_rels)

    if {"use-after-donate", "donation-vector"} & rules:
        for mi in mods.values():
            findings.extend(check_donation(mi))
    if {"host-sync", "tracer-control-flow", "traced-time"} & rules:
        for rel, mi in mods.items():
            if rel.startswith("tools/"):
                continue  # tools drive engines, they don't trace
            findings.extend(check_trace(mi))
    if {"unguarded-write", "unguarded-read", "bad-annotation"} & rules:
        lock_mods = {
            rel: mi for rel, mi in mods.items() if rel in LOCK_FILES
        }
        findings.extend(check_locks(lock_mods))
    if {"metric-drift", "env-drift", "fault-drift",
            "flag-drift"} & rules:
        findings.extend(check_drift(root, mods))

    # suppressions + bad-suppression findings
    kept: List[Finding] = []
    for f in findings:
        if f.rule not in rules:
            continue
        mi = mods.get(f.path)
        if mi is not None:
            sup = mi.suppress.get(f.line, set())
            if "*" in sup or f.rule in sup:
                continue
        kept.append(f)
    if "bad-suppression" in rules:
        for rel, mi in mods.items():
            for line in mi.bad_suppressions:
                kept.append(Finding(
                    "bad-suppression", rel, line,
                    "graftcheck: ignore[...] without a '-- reason' — "
                    "every suppression must justify itself",
                ))
    seen: Set[Tuple] = set()
    out = []
    for f in sorted(kept, key=Finding.key):
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="JAX-aware static analysis: donation, trace "
        "hazards, lock discipline, artifact drift "
        "(docs/static_analysis.md)",
    )
    ap.add_argument("--root", default=REPO, help="repo root to analyze")
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all); see "
        "--list-rules",
    )
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-env", action="store_true",
                    help="dump the env vars the drift pass extracted")
    ap.add_argument("--list-metrics", action="store_true",
                    help="dump the metric families extracted from code")
    ap.add_argument("--list-faults", action="store_true",
                    help="dump the fault points extracted from code")
    args = ap.parse_args(argv)

    if args.list_rules:
        print("\n".join(ALL_RULES))
        return 0
    if args.list_env or args.list_metrics or args.list_faults:
        code = load_modules(args.root, python_files(
            args.root, ("mlcomp_tpu", "bench.py", "tools")
        ) + ["__graft_entry__.py"])
        if args.list_env:
            for name, sites in sorted(collect_env_vars(code).items()):
                rel, line, kind = sites[0]
                print(f"{name}\t{kind}\t{rel}:{line}")
        if args.list_metrics:
            sel = {
                rel: mi for rel, mi in code.items()
                if rel in ("mlcomp_tpu/engine.py", "mlcomp_tpu/serve.py")
                or rel.startswith("mlcomp_tpu/obs/")
            }
            for name, (rel, line) in sorted(
                collect_code_metrics(sel).items()
            ):
                print(f"{name}\t{rel}:{line}")
        if args.list_faults:
            for p, (rel, line) in sorted(
                collect_fault_points(code).items()
            ):
                print(f"{p}\t{rel}:{line}")
        return 0

    rules: Optional[Set[str]] = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(ALL_RULES)
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2
    findings = run_passes(args.root, rules)
    if args.as_json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"graftcheck: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `graftcheck --list-... | head` is fine
        os._exit(0)

"""Round-4 late sweep: remaining decode-shape block candidates.
gate_up fused (2048, 16384) and lm_head (2048, 32768) at 2 MB vs 4 MB
blocks; decode_attention blk 384 vs 768 at l_buf 2304.  One process,
marginal fori_loop timing, interleaved, median of 7."""
import statistics
import time

import jax
import jax.numpy as jnp

from mlcomp_tpu.ops.pallas.decode_attention import decode_attention
from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
from mlcomp_tpu.ops.quant import quantize_leaf

B, D = 8, 2048
key = jax.random.PRNGKey(0)


def qw(d_in, d_out, k):
    w = jax.random.normal(jax.random.fold_in(key, k), (d_in, d_out), jnp.float32)
    leaf = quantize_leaf(w)
    return leaf["q8"], leaf["q8_scale"].reshape(-1)


gu, gus = qw(D, 16384, 1)
hd, hds = qw(D, 32768, 2)

HKV, DH, LBUF = 16, 128, 2304
k8 = jax.random.randint(key, (B, HKV, LBUF, DH), -127, 127, jnp.int8)
v8 = jax.random.randint(jax.random.fold_in(key, 3), (B, HKV, LBUF, DH), -127, 127, jnp.int8)
ks = jax.random.uniform(jax.random.fold_in(key, 4), (B, HKV, 1, LBUF)) * 0.01
vs = jax.random.uniform(jax.random.fold_in(key, 5), (B, HKV, 1, LBUF)) * 0.01
start = jnp.zeros((B,), jnp.int32)
stop = jnp.full((B,), 2200, jnp.int32)


def mm(w, s, bn, bd):
    def f(x):
        y = quant_matmul(x[:, :D], w, s, block_n=bn, block_d=bd)
        return jnp.tile(y[:, :D] * 1e-3, (1, 1))

    return f, w.size / 819e9 * 1e6


def attn(blk):
    def f(x):
        q = x[:, :HKV * DH].reshape(B, HKV, DH).astype(jnp.bfloat16)
        o = decode_attention(q, k8, ks, v8, vs, kv_start=start,
                             kv_stop=stop, block_kv=blk)
        return jnp.tile((o.reshape(B, HKV * DH)[:, :D] * 1e-3 + x[:, :D] * .5), (1, 1))

    return f, 2 * HKV * 2200 * DH / 819e9 * 1e6 * 1  # per row? no: per call below


CASES = {
    "gu_n2048_d2048": mm(gu, gus, 2048, 2048),   # 8 steps of 4MB (today)
    "gu_n1024_d2048": mm(gu, gus, 1024, 2048),   # 16 steps of 2MB
    "gu_n512_d2048": mm(gu, gus, 512, 2048),     # 32 steps of 1MB
    "hd_n2048_d2048": mm(hd, hds, 2048, 2048),   # 16 steps of 4MB (today)
    "hd_n1024_d2048": mm(hd, hds, 1024, 2048),   # 32 steps of 2MB
    "attn_blk768": attn(768),
    "attn_blk384": attn(384),
}
CASES["attn_blk768"] = (CASES["attn_blk768"][0], 2 * B * HKV * 2200 * DH / 819e9 * 1e6)
CASES["attn_blk384"] = (CASES["attn_blk384"][0], 2 * B * HKV * 2200 * DH / 819e9 * 1e6)

N_LO, N_HI = 128, 1536


def looped(f, n):
    return jax.jit(lambda x: jax.lax.fori_loop(
        0, n, lambda i, h: f(h).astype(jnp.bfloat16), x
    ))


x0 = jax.random.normal(jax.random.fold_in(key, 99), (B, D), jnp.bfloat16)
fns = {}
for nm, (f, _) in CASES.items():
    for n in (N_LO, N_HI):
        fns[(nm, n)] = looped(f, n)
for kk, fn in fns.items():
    t0 = time.perf_counter()
    float(fn(x0)[0, 0])
    print(f"  {kk}: {time.perf_counter()-t0:.1f}s", flush=True)

times = {k: [] for k in fns}
for _ in range(7):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        float(fn(x0)[0, 0])
        times[kk].append(time.perf_counter() - t0)

for nm, (_, roof) in CASES.items():
    t_lo = statistics.median(times[(nm, N_LO)])
    t_hi = statistics.median(times[(nm, N_HI)])
    per = (t_hi - t_lo) / (N_HI - N_LO) * 1e6
    print(f"{nm:16s}: {per:8.2f} us/call  roofline {roof:6.1f} "
          f"({roof/per*100 if per>0 else 0:5.1f}%)")

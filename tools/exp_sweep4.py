"""qkv-shape (2048x6144) block_n sweep: the uniform n=512 rule gave 12
steps of 1MB (in-situ 18.3 us vs 15.6 at the old 1536 block).  Test
512/768/1024/1536 in one process; confirm (2048, 2048) keeps 512."""
import statistics
import time

import jax
import jax.numpy as jnp

from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
from mlcomp_tpu.ops.quant import quantize_leaf

B, D = 8, 2048
key = jax.random.PRNGKey(0)


def qw(d_in, d_out, k):
    w = jax.random.normal(jax.random.fold_in(key, k), (d_in, d_out), jnp.float32)
    leaf = quantize_leaf(w)
    return leaf["q8"], leaf["q8_scale"].reshape(-1)


qk, qks = qw(D, 6144, 1)
sq, sqs = qw(D, D, 2)

CASES = {
    "qkv_n512": (qk, qks, 512),
    "qkv_n768": (qk, qks, 768),
    "qkv_n1024": (qk, qks, 1024),
    "qkv_n1536": (qk, qks, 1536),
    "sq_n512": (sq, sqs, 512),
    "sq_n1024": (sq, sqs, 1024),
}
N_LO, N_HI = 128, 1536


def looped(spec, n):
    w, s, bn = spec

    def f(x):
        y = quant_matmul(x, w, s, block_n=bn, block_d=2048)
        return (y[:, :D] * 1e-3).astype(jnp.bfloat16)

    return jax.jit(lambda x: jax.lax.fori_loop(0, n, lambda i, h: f(h), x))


x0 = jax.random.normal(jax.random.fold_in(key, 99), (B, D), jnp.bfloat16)
fns = {}
for nm, spec in CASES.items():
    for n in (N_LO, N_HI):
        fns[(nm, n)] = looped(spec, n)
for kk, fn in fns.items():
    t0 = time.perf_counter()
    float(fn(x0)[0, 0])
    print(f"  {kk}: {time.perf_counter()-t0:.1f}s", flush=True)

times = {k: [] for k in fns}
for _ in range(7):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        float(fn(x0)[0, 0])
        times[kk].append(time.perf_counter() - t0)

for nm, spec in CASES.items():
    t_lo = statistics.median(times[(nm, N_LO)])
    t_hi = statistics.median(times[(nm, N_HI)])
    per = (t_hi - t_lo) / (N_HI - N_LO) * 1e6
    roof = spec[0].size / 819e9 * 1e6
    print(f"{nm:12s}: {per:8.2f} us/call  roofline {roof:5.1f} "
          f"({roof/per*100 if per>0 else 0:5.1f}%)")

# RESULT (recorded for honesty): this sweep produced physically
# impossible readings (qkv_n768 at 111% of the HBM roofline, sq_n1024 at
# 223%) — the N_LO and N_HI loops are SEPARATE compiles, and the
# tunnel's nondeterministic kernel scheduling can make the marginal
# difference meaningless at few-us signals.  Micro-sweeps are only
# trustworthy when the same pallas variant appears in both programs
# with consistent schedules; the end-to-end decode marginal (one scan
# program at two trip counts, stable across many sessions) is the
# arbiter for any default change.  The qkv n=512 default therefore
# stands on the e2e evidence (2184/2195 tok/s), not on this sweep.

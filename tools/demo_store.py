"""Seed a demo task store so the report dashboard has something to show.

Populates every surface the dashboard renders: two DAGs (one finished
with mixed task outcomes, one mid-flight so the stop/restart action
links appear), per-task metric series, logs, a classification report
(PR curves + confusion + worst-mistake gallery), a segmentation report,
a declared layout artifact, and worker heartbeats with host metrics.

Usage::

    python tools/demo_store.py /tmp/demo.db
    python -m mlcomp_tpu.cli report --db /tmp/demo.db --port 8765

Used by the round-5 browser verification of the dashboard JS (SURVEY
§6): the ~250 lines of chart/DAG/action script had only ever been
curl-verified; this store plus a real browser executes them all.
"""

from __future__ import annotations

import sys

import numpy as np

from mlcomp_tpu.dag.schema import DagSpec, TaskSpec, TaskStatus
from mlcomp_tpu.db.store import Store
from mlcomp_tpu.report.artifacts import (
    classification_report,
    layout_payload,
    segmentation_report,
)


def run_task(store, dag_id, name, worker, status=TaskStatus.SUCCESS,
             error=None):
    """Drive one task through the real lifecycle (queue -> claim ->
    finish) so worker/started/finished columns fill in like production."""
    store.set_task_status(dag_id, [name], TaskStatus.QUEUED)
    row = store.claim_task(worker, free_chips=1024, free_hosts=64)
    assert row is not None and row["name"] == name, (name, row)
    if status is not TaskStatus.IN_PROGRESS:
        store.finish_task(row["id"], status, error=error)
    return row["id"]


def curve(n, start, end, noise, rng, floor=None):
    xs = np.arange(n)
    decay = start + (end - start) * (1 - np.exp(-3.0 * xs / n))
    vals = decay + rng.normal(0, noise, n)
    if floor is not None:
        vals = np.maximum(vals, floor)
    return [(int(s * 50), float(v)) for s, v in zip(xs, vals)]


def seed(path: str) -> None:
    rng = np.random.default_rng(0)
    store = Store(path)

    # --- DAG 1: finished grid experiment with one failure -------------
    tasks = [
        TaskSpec(name="prepare", executor="shell", stage="data"),
        TaskSpec(name="train_lr_1e-3", executor="train", depends=("prepare",),
                 stage="train", grid_index=0,
                 grid_params=(("lr", 1e-3),)),
        TaskSpec(name="train_lr_3e-4", executor="train", depends=("prepare",),
                 stage="train", grid_index=1,
                 grid_params=(("lr", 3e-4),)),
        TaskSpec(name="train_lr_1e-4", executor="train", depends=("prepare",),
                 stage="train", grid_index=2,
                 grid_params=(("lr", 1e-4),)),
        TaskSpec(name="valid_best", executor="valid",
                 depends=("train_lr_1e-3", "train_lr_3e-4", "train_lr_1e-4"),
                 stage="valid"),
        TaskSpec(name="infer_test", executor="infer", depends=("valid_best",),
                 stage="infer"),
    ]
    dag1 = store.submit_dag(DagSpec(
        name="cifar_grid", project="demo", tasks=tuple(tasks)))

    tid = run_task(store, dag1, "prepare", "tpu-vm-0")
    store.log(tid, "INFO", "tokenized 50k samples")

    for i, (name, lr) in enumerate(
            [("train_lr_1e-3", 1e-3), ("train_lr_3e-4", 3e-4),
             ("train_lr_1e-4", 1e-4)]):
        if name == "train_lr_1e-4":   # one failed leg: error column + chip
            tid = run_task(store, dag1, name, "tpu-vm-0",
                           status=TaskStatus.FAILED,
                           error="loss diverged at step 450")
            store.log(tid, "ERROR", "nan loss at step 450, aborting")
            for s, v in curve(9, 2.3, 8.0, 0.3, rng):
                store.metric(tid, "train/loss", v, s)
            continue
        tid = run_task(store, dag1, name, "tpu-vm-0")
        loss = curve(40, 2.3, 0.4 + 0.1 * i, 0.05, rng, floor=0.05)
        acc = curve(40, 0.1, 0.92 - 0.03 * i, 0.01, rng)
        for s, v in loss:
            store.metric(tid, "train/loss", v, s)
        for s, v in acc:
            store.metric(tid, "valid/accuracy", min(v, 0.99), s)
        store.metric(tid, "lr", lr, 0)
        store.log(tid, "INFO", f"started with lr={lr}")
        store.log(tid, "INFO", f"finished: accuracy {acc[-1][1]:.4f}")

    # valid_best: classification report + declared layout
    tid = run_task(store, dag1, "valid_best", "tpu-vm-1")
    n, k = 600, 4
    y = rng.integers(0, k, n)
    logits = rng.normal(0, 1, (n, k))
    logits[np.arange(n), y] += 2.2          # mostly-right model
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    store.add_report(tid, "valid_cls", classification_report(
        y, probs, class_names=["plane", "car", "bird", "cat"]))
    store.add_report(tid, "layout", layout_payload([
        {"type": "series", "metrics": ["valid/accuracy"],
         "title": "accuracy (declared layout)"},
        {"type": "summary"}, {"type": "pr_curves"}, {"type": "confusion"},
    ]))
    for s in range(12):
        store.metric(tid, "valid/accuracy",
                     0.7 + 0.02 * s + rng.normal(0, 0.004), s * 100)
    store.log(tid, "INFO", "selected train_lr_1e-3 as best")

    # infer_test: segmentation report (exercises the other renderer)
    tid = run_task(store, dag1, "infer_test", "tpu-vm-1")
    yt = rng.integers(0, 3, (8, 32, 32))
    yp = yt.copy()
    flip = rng.random(yt.shape) < 0.12
    yp[flip] = rng.integers(0, 3, int(flip.sum()))
    store.add_report(tid, "seg_eval", segmentation_report(
        yt, yp, class_names=["bg", "road", "car"]))
    store.log(tid, "INFO", "wrote 8 masks")

    # --- DAG 2: mid-flight (stop links + warn chips + graph colors) ---
    tasks2 = [
        TaskSpec(name="tokenize", executor="shell", stage="data"),
        TaskSpec(name="pretrain", executor="train", depends=("tokenize",),
                 stage="train"),
        TaskSpec(name="eval_ppl", executor="valid", depends=("pretrain",),
                 stage="valid"),
    ]
    dag2 = store.submit_dag(DagSpec(
        name="lm_pretrain", project="demo", tasks=tuple(tasks2)))
    run_task(store, dag2, "tokenize", "tpu-vm-0")
    pre = run_task(store, dag2, "pretrain", "tpu-vm-0",
                   status=TaskStatus.IN_PROGRESS)
    for s, v in curve(25, 9.8, 3.1, 0.08, rng):
        store.metric(pre, "train/loss", v, s)
    store.metric(pre, "train/tokens_per_sec", 17404.7, 0)
    store.log(pre, "INFO", "step 1250: loss 3.41")

    # --- workers ------------------------------------------------------
    store.heartbeat("tpu-vm-0", chips=4, busy_chips=4, info={
        "load1": 3.2, "mem_free_gb": 187.4,
        "tasks": [pre],
    })
    store.heartbeat("tpu-vm-1", chips=4, busy_chips=0, info={
        "load1": 0.1, "mem_free_gb": 305.0, "tasks": [],
    })
    store.heartbeat("tpu-vm-2", chips=4, busy_chips=0,
                    info={"load1": 0.0, "mem_free_gb": 300.1, "tasks": []})
    store.mark_worker_dead("tpu-vm-2")

    store.close()
    print(f"seeded {path}: 2 dags, {len(tasks) + len(tasks2)} tasks")


if __name__ == "__main__":
    seed(sys.argv[1] if len(sys.argv) > 1 else "/tmp/mlcomp_demo.db")

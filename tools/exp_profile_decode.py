"""Profile the b8_kv8_int8 decode step: capture a device trace of the
token loop and aggregate per-kernel durations, so the remaining
roofline gap is attributed, not guessed.  (Wall times through the
tunnel inflate ~8x; per-kernel device durations are trustworthy —
memory note + round-3 finding.)"""
import collections
import glob
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.ops.quant import quantize_params
from mlcomp_tpu.train.state import init_model

LM_VOCAB, LM_HIDDEN, LM_LAYERS, LM_HEADS = 32768, 2048, 16, 16
N_NEW = 16

cfg = {
    "name": "transformer_lm", "vocab_size": LM_VOCAB, "hidden": LM_HIDDEN,
    "layers": LM_LAYERS, "heads": LM_HEADS, "mlp_dim": 4 * LM_HIDDEN,
    "dtype": "bfloat16", "decode_fused": True, "kv_quant": True,
}
model = create_model(cfg)
gen = np.random.default_rng(2)
prompt = jnp.asarray(gen.integers(1, LM_VOCAB, size=(8, 2048)), jnp.int32)
params, _ = init_model(model, {"x": prompt[:1, :128]}, jax.random.PRNGKey(0))
qvars = {"params": quantize_params(params)}
del params

fn = jax.jit(partial(generate, model, max_new_tokens=N_NEW, quant_kernel=True))
t0 = time.perf_counter()
int(fn(qvars, prompt)[0, -1])
print(f"compiled {time.perf_counter()-t0:.0f}s", flush=True)

trace_dir = "/tmp/decode_trace"
os.system(f"rm -rf {trace_dir}")
with jax.profiler.trace(trace_dir):
    int(fn(qvars, prompt)[0, -1])

pb = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
print("xplane files:", pb, flush=True)
# dependency-free reader (mlcomp_tpu/obs/devprof.py) — no TF install
# needed; same wire truth the tensorflow.tsl protobufs decoded
from mlcomp_tpu.obs.devprof import load_xspace, short_op as short

for plane in load_xspace(pb[0]):
    if "TPU" not in plane.name and "tpu" not in plane.name:
        continue
    print(f"\n=== plane: {plane.name} ===")
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        # locate the token-loop while span; aggregate only events inside
        wh = [ev for ev in line.events if short(ev.name) == "while"]
        if not wh:
            print("no while span found")
            continue
        wh = max(wh, key=lambda e: e.duration_ps)
        lo, hi = wh.offset_ps, wh.offset_ps + wh.duration_ps
        print(f"while span: {wh.duration_ps/1e9:.2f} ms "
              f"(/{N_NEW - 1} steps = {wh.duration_ps/1e9/(N_NEW-1):.3f})")
        total = collections.Counter()
        counts = collections.Counter()
        for ev in line.events:
            if ev.name == wh.name:
                continue
            if not (lo <= ev.offset_ps and ev.offset_ps < hi):
                continue
            total[short(ev.name)] += ev.duration_ps / 1e6  # us
            counts[short(ev.name)] += 1
        grand = sum(total.values())
        steps = N_NEW - 1
        print(f"in-while op total: {grand/1e3:.2f} ms "
              f"({grand/1e3/steps:.3f} ms/step if no overlap)")
        for nm, us in total.most_common(30):
            print(f"  {us/steps:8.1f} us/step  x{counts[nm]/steps:6.1f}  {nm}")
        # break copies/DUS down by result shape to find the producers
        shp = collections.Counter()
        scount = collections.Counter()
        for ev in line.events:
            nm = ev.name
            key = short(nm)
            if key not in ("copy", "dynamic_update_slice", "broadcast_in_dim"):
                continue
            if not (lo <= ev.offset_ps < hi):
                continue
            sig = key + "  " + nm.split(" = ")[1].split("(")[0][:70]
            shp[sig] += ev.duration_ps / 1e6
            scount[sig] += 1
        print("\ncopy/DUS by shape:")
        for sig, us in shp.most_common(14):
            print(f"  {us/steps:8.1f} us/step  x{scount[sig]/steps:6.1f}  {sig}")

"""Profile ONE continuous-engine K-step dispatch (1.2B all-int8, the
bench_engine config) and aggregate in-scan per-op device durations —
attributing the engine's ~9.0 ms marginal step vs the generate scan's
3.67 (round-5 finding: the host unpack loop measured FREE, so the gap
is device-side; this names the ops).  Same xplane methodology as
exp_profile_decode.py (device durations are tunnel-trustworthy)."""
import collections
import glob
import os
import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np

from mlcomp_tpu.engine import DecodeEngine, _POISON
from mlcomp_tpu.models import create_model
from mlcomp_tpu.ops.quant import quantize_params
from mlcomp_tpu.train.state import init_model

LM_VOCAB, LM_HIDDEN, LM_LAYERS, LM_HEADS = 32768, 2048, 16, 16
DEC_PROMPT, DEC_NEW, K = 2048, 256, 8

cfg = {
    "name": "transformer_lm", "vocab_size": LM_VOCAB, "hidden": LM_HIDDEN,
    "layers": LM_LAYERS, "heads": LM_HEADS, "mlp_dim": 4 * LM_HIDDEN,
    "dtype": "bfloat16", "decode_fused": True, "kv_quant": True,
}
model = create_model(cfg)
gen = np.random.default_rng(2)
p128 = jnp.asarray(gen.integers(1, LM_VOCAB, size=(1, 128)), jnp.int32)
params, _ = init_model(model, {"x": p128}, jax.random.PRNGKey(0))
qvars = {"params": quantize_params(params)}
del params


def make_req():
    return {
        "ids": gen.integers(1, LM_VOCAB, size=DEC_PROMPT).tolist(),
        "n_new": DEC_NEW, "future": Future(), "temperature": 0.0,
        "top_k": LM_VOCAB, "top_p": 1.0, "eos_id": -1, "logprobs": False,
        "repetition_penalty": 1.0, "stream": None,
        "t_submit": time.perf_counter(),
    }


eng = DecodeEngine(model, qvars, slots=8, prompt_buckets=(DEC_PROMPT,),
                   max_new_cap=DEC_NEW, quant_kernel=True,
                   steps_per_dispatch=K)
eng._stop.set()
eng._queue.put(_POISON)
eng._thread.join(timeout=30)
for _ in range(8):
    eng._start_admission(make_req())
    while eng._adm is not None:
        eng._run_admission_chunk()
t0 = time.perf_counter()
eng._run_dispatch()
eng._run_dispatch()
print(f"warm {time.perf_counter()-t0:.0f}s", flush=True)

trace_dir = "/tmp/engine_trace"
os.system(f"rm -rf {trace_dir}")
with jax.profiler.trace(trace_dir):
    eng._run_dispatch()

pb = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
print("xplane files:", pb, flush=True)
# dependency-free reader (mlcomp_tpu/obs/devprof.py) — no TF install
# needed; same wire truth the tensorflow.tsl protobufs decoded
from mlcomp_tpu.obs.devprof import load_xspace, short_op as short

for plane in load_xspace(pb[0]):
    if "TPU" not in plane.name and "tpu" not in plane.name:
        continue
    print(f"\n=== plane: {plane.name} ===")
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        wh = [ev for ev in line.events if short(ev.name) == "while"]
        if not wh:
            print("no while span found")
            continue
        wh = max(wh, key=lambda e: e.duration_ps)
        lo, hi = wh.offset_ps, wh.offset_ps + wh.duration_ps
        print(f"K-step scan span: {wh.duration_ps/1e9:.2f} ms "
              f"(/{K} steps = {wh.duration_ps/1e9/K:.3f} ms/step)")
        total = collections.Counter()
        counts = collections.Counter()
        for ev in line.events:
            if ev.name == wh.name:
                continue
            if not (lo <= ev.offset_ps < hi):
                continue
            total[short(ev.name)] += ev.duration_ps / 1e6  # us
            counts[short(ev.name)] += 1
        grand = sum(total.values())
        print(f"in-scan op total: {grand/1e3:.2f} ms "
              f"({grand/1e3/K:.3f} ms/step if no overlap)")
        for nm, us in total.most_common(30):
            print(f"  {us/K:8.1f} us/step  x{counts[nm]/K:6.1f}  {nm}")

# Host-side pipeline A/B: the same dispatch driven synchronous
# (issue + resolve) vs double-buffered (issue N+1 before resolving N).
# The device per-op durations above are depth-invariant; the wall
# delta here is purely the host dispatch overhead the in-flight
# pipeline hides.
walls = {1: [], 2: []}
for _ in range(3):
    t0 = time.perf_counter()
    eng._run_dispatch()
    walls[1].append(time.perf_counter() - t0)
    eng._issue_dispatch()  # prime outside the clock
    t0 = time.perf_counter()
    eng._issue_dispatch()
    eng._process_oldest()
    walls[2].append(time.perf_counter() - t0)
    while eng._inflight:
        eng._process_oldest()
d1, d2 = (1e3 * min(walls[k]) for k in (1, 2))
print(f"\npipeline A/B (host wall per dispatch, best of 3): "
      f"depth1 {d1:.1f} ms, depth2 {d2:.1f} ms, "
      f"hidden {max(d1 - d2, 0.0):.1f} ms")

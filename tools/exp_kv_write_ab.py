"""ONE-process A/B of the int8 KV cache's single-token update layout:
{reshape, transpose} x {where, dus} scale writes, on the full 1.2B
b8_kv8_int8 decode (marginal 128-vs-256-token timing, interleaved,
median of 5).  Cross-process runs contradicted each other (the tunnel
compile service is nondeterministic); this settles it."""
import itertools
import statistics
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

import mlcomp_tpu.models.transformer as tr
from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.ops.quant import quantize_params
from mlcomp_tpu.train.state import init_model

LM_VOCAB, LM_HIDDEN, LM_LAYERS, LM_HEADS = 32768, 2048, 16, 16
DEC_PROMPT, DEC_NEW = 2048, 256

cfg = {
    "name": "transformer_lm", "vocab_size": LM_VOCAB, "hidden": LM_HIDDEN,
    "layers": LM_LAYERS, "heads": LM_HEADS, "mlp_dim": 4 * LM_HIDDEN,
    "dtype": "bfloat16", "decode_fused": True, "kv_quant": True,
}
model = create_model(cfg)
gen = np.random.default_rng(2)
prompt = jnp.asarray(gen.integers(1, LM_VOCAB, size=(8, DEC_PROMPT)), jnp.int32)
params, _ = init_model(model, {"x": prompt[:1, :128]}, jax.random.PRNGKey(0))
qvars = {"params": quantize_params(params)}
del params

fns = {}
for reshape, sw in itertools.product((True, False), ("where", "dus")):
    tr._KV_UPDATE_RESHAPE = reshape
    tr._KV_SCALE_WRITE = sw
    for n_new in (DEC_NEW // 2, DEC_NEW):
        key = (reshape, sw, n_new)
        fns[key] = jax.jit(
            partial(generate, model, max_new_tokens=n_new, quant_kernel=True)
        )
        t0 = time.perf_counter()
        int(fns[key](qvars, prompt)[0, -1])
        print(f"  {key}: compiled {time.perf_counter()-t0:.0f}s", flush=True)

times = {k: [] for k in fns}
for _ in range(5):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        int(fn(qvars, prompt)[0, -1])
        times[kk].append(time.perf_counter() - t0)

for reshape, sw in itertools.product((True, False), ("where", "dus")):
    dt = (statistics.median(times[(reshape, sw, DEC_NEW)])
          - statistics.median(times[(reshape, sw, DEC_NEW // 2)]))
    ms = dt / (DEC_NEW // 2) * 1e3
    tps = 8 * (DEC_NEW // 2) / dt
    print(f"reshape={reshape!s:5s} scale={sw:5s}: {ms:6.3f} ms/step  "
          f"{tps:7.1f} tok/s")

"""End-to-end check of round-4 decode work: b8_kv8_int8 (fused layout +
auto blocks) vs its roofline, plus b8_kv8 for reference.  Same marginal
protocol as bench.py's decode line, fewer variants."""
import os
import statistics
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mlcomp_tpu.models import create_model
from mlcomp_tpu.models.generation import generate
from mlcomp_tpu.ops.quant import quantize_params
from mlcomp_tpu.train.state import init_model

LM_VOCAB, LM_HIDDEN, LM_LAYERS, LM_HEADS = 32768, 2048, 16, 16
DEC_PROMPT, DEC_NEW = 2048, 256
V5E_HBM_BW = 819e9

lm_cfg = {
    "name": "transformer_lm", "vocab_size": LM_VOCAB, "hidden": LM_HIDDEN,
    "layers": LM_LAYERS, "heads": LM_HEADS, "mlp_dim": 4 * LM_HIDDEN,
    "dtype": "bfloat16", "decode_fused": True, "kv_quant": True,
}
model_kv8 = create_model(lm_cfg)
gen = np.random.default_rng(2)
prompt = jnp.asarray(gen.integers(1, LM_VOCAB, size=(8, DEC_PROMPT)), jnp.int32)
params, _ = init_model(model_kv8, {"x": prompt[:1, :128]}, jax.random.PRNGKey(0))
qvars = {"params": quantize_params(params)}
del params

modes = {"kv8_int8": True, "kv8": False}
fns = {}
for mode, qk in modes.items():
    for n_new in (DEC_NEW // 2, DEC_NEW):
        fns[(mode, n_new)] = jax.jit(
            partial(generate, model_kv8, max_new_tokens=n_new, quant_kernel=qk)
        )
for kk, fn in fns.items():
    t0 = time.perf_counter()
    int(fn(qvars, prompt)[0, -1])
    print(f"  {kk}: compiled {time.perf_counter()-t0:.0f}s", flush=True)

times = {k: [] for k in fns}
for _ in range(5):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        int(fn(qvars, prompt)[0, -1])
        times[kk].append(time.perf_counter() - t0)

d = LM_HIDDEN
weight_bytes_bf16 = sum(
    int(np.prod(s)) for s in [
        *[(d, d)] * 4 * LM_LAYERS,
        *[(d, 4 * d)] * 3 * LM_LAYERS,
        (d, LM_VOCAB),
    ]
) * 2
kv_bytes_int8 = (DEC_PROMPT + DEC_NEW) * LM_LAYERS * 2 * (d + 4 * LM_HEADS)
for mode in modes:
    dt = (statistics.median(times[(mode, DEC_NEW)])
          - statistics.median(times[(mode, DEC_NEW // 2)]))
    n_tok = 8 * (DEC_NEW // 2)
    w = weight_bytes_bf16 * (0.5 if mode.endswith("int8") else 1.0)
    roof = 8 * V5E_HBM_BW / (w + 8 * kv_bytes_int8)
    tps = n_tok / dt
    print(f"b8_{mode}: {tps:.1f} tok/s  roofline {roof:.1f}  "
          f"({tps/roof*100:.1f}%)  ms/tok/seq {dt/n_tok*8*1e3:.3f}")

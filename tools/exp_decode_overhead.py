"""Experiment: where does b8_kv8_int8's roofline gap go? (round-4 #1)

Finding from count analysis: one decode step at B=8/d=2048/L=16 runs
~4352 quant_matmul GRID STEPS (block 512x512 = 256KB each, ~0.31us of
DMA) — per-grid-step overhead (~0.5-1us, same disease decode_attention
cured) explains the ~2.2ms gap.  This experiment A/Bs block shapes at
the three decode GEMV shapes, in one process, marginal fori_loop
timing (N=256 vs N=4096, diff/3840), interleaved, median of 7.
"""
import statistics
import time

import jax
import jax.numpy as jnp

from mlcomp_tpu.ops.pallas.quant_matmul import quant_matmul
from mlcomp_tpu.ops.quant import quantize_leaf

B, D, M = 8, 2048, 8192
key = jax.random.PRNGKey(0)


def qw(d_in, d_out, k):
    w = jax.random.normal(jax.random.fold_in(key, k), (d_in, d_out), jnp.float32)
    leaf = quantize_leaf(w)
    return leaf["q8"], leaf["q8_scale"].reshape(-1)


sq, ss = qw(D, D, 1)        # square: q/k/v/out shape
gu, gus = qw(D, M, 4)       # gate/up shape
dn, dns = qw(M, D, 6)       # down shape

hd, hds = qw(D, 32768, 7)   # lm_head shape

# name -> (qmat, scale, in_dim, block_n, block_d)
CASES = {
    "sq_fatd": (sq, ss, D, 512, 2048),          # 4 steps of 1MB
    "sq_fatd_b": (sq, ss, D, 512, 2048),        # same again: stability check
    "gu_n512_fatd": (gu, gus, D, 512, 2048),    # 16 steps of 1MB
    "gu_n1024_fatd": (gu, gus, D, 1024, 2048),  # 8 steps of 2MB
    "gu_n2048_fatd": (gu, gus, D, 2048, 2048),  # 4 steps of 4MB
    "dn_n512_d4096": (dn, dns, M, 512, 4096),   # 8 steps of 2MB
    "dn_n1024_d4096": (dn, dns, M, 1024, 4096), # 4 steps of 4MB
    "hd_n1024_fatd": (hd, hds, D, 1024, 2048),  # 32 steps of 2MB
    "hd_n2048_fatd": (hd, hds, D, 2048, 2048),  # 16 steps of 4MB
    "hd_512x512": (hd, hds, D, 512, 512),       # today: 256 steps
}

N_LO, N_HI = 128, 2048


def looped(qmat, scale, d_in, bn, bd, n):
    def body(i, x):
        y = quant_matmul(x[:, :d_in], qmat, scale, block_n=bn, block_d=bd)
        # fold output back to a (B, M) carry regardless of out width
        y = jnp.tile(y[:, :D], (1, M // D))
        return y * 1e-3

    return jax.jit(
        lambda x: jax.lax.fori_loop(0, n, body, jnp.tile(x, (1, M // D)))
    )


x0 = jax.random.normal(jax.random.fold_in(key, 99), (B, D), jnp.bfloat16)
fns = {}
for name, spec in CASES.items():
    for n in (N_LO, N_HI):
        fns[(name, n)] = looped(*spec, n)

print("compiling...", flush=True)
for (name, n), fn in fns.items():
    t0 = time.perf_counter()
    float(fn(x0)[0, 0])
    print(f"  {name} n={n}: {time.perf_counter()-t0:.1f}s", flush=True)

times = {k: [] for k in fns}
for w in range(7):
    for kk, fn in fns.items():
        t0 = time.perf_counter()
        float(fn(x0)[0, 0])
        times[kk].append(time.perf_counter() - t0)

print()
for name, (qmat, _, _, _, _) in CASES.items():
    t_lo = statistics.median(times[(name, N_LO)])
    t_hi = statistics.median(times[(name, N_HI)])
    per = (t_hi - t_lo) / (N_HI - N_LO) * 1e6
    roof = qmat.size / 819e9 * 1e6
    print(f"{name:16s}: {per:8.2f} us/iter  roofline {roof:6.2f} us "
          f"({roof / per * 100 if per > 0 else 0:5.1f}%)")

"""Continuous-batching decode engine: token-granularity serving.

The round-3 serving daemon batched at REQUEST granularity: a window
batcher grouped arrivals, ran one ``generate`` per group, and a
128-token generation blocked every later arrival for its whole decode
(round-3 verdict, missing #3).  The building blocks for better were
already in place — per-row KV windows, per-row sampling knobs, static
bucketed shapes — this module uses them at their natural granularity:

- a fixed pool of ``slots`` decode rows runs ONE compiled single-token
  step; every step each active row samples, forwards, and streams its
  token out;
- a new request PREFILLS alone (one compiled program per prompt
  bucket, B=1) and its cache rows are INSERTED into a free slot at the
  next step boundary — arrival-to-first-token is one step, independent
  of how deep the other rows are in their decodes;
- finished rows free their slot immediately — no drain barrier, and
  queue order is FIFO over free slots, so the round-3 batcher's
  starvation window (a request re-queued behind an endless stream of
  the other bucket) cannot be constructed;
- per-row cache cursors (``cache_cursor``, models/transformer.py) let
  every row sit at a different depth in the shared cache buffers.

TPU-first consequences: shapes never change (slot count, buffer length
and prompt buckets are static), so the engine compiles `1 + #buckets +
1` programs total; the step program's carry (cache, logits, presence)
is donated, so the cache updates stay in-place; sampling knobs ride as
traced (slots,) arrays — any knob mix shares the one step program.

The host drives one dispatch per token step.  On a directly-attached
TPU that dispatch is tens of microseconds against a multi-ms step; the
``generate`` scan path (zero dispatches) remains the right tool for
OFFLINE batch generation, and stays the engine of the window batcher.

No upstream analog: the reference framework has no serving path at all.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _fail_future(fut: Future, err: Exception) -> None:
    """Fail a future idempotently: submit's close-race check and close's
    queue drain can both reach the same future — a bare done()-then-
    set_exception pair races to InvalidStateError."""
    try:
        if not fut.done():
            fut.set_exception(err)
    except Exception:  # InvalidStateError: the other side resolved it
        pass


class _Slot:
    __slots__ = (
        "req", "cursor", "position", "start", "remaining", "emitted",
    )

    def __init__(self, req, cursor, position, start, remaining):
        self.req = req
        self.cursor = cursor          # next cache slot this row writes
        self.position = position      # next RoPE position (real tokens)
        self.start = start            # first valid cache slot (pads before)
        self.remaining = remaining    # tokens still allowed
        self.emitted: List[int] = []


class DecodeEngine:
    """Fixed-slot continuous batcher around a decode-capable model.

    ``submit`` returns a Future resolving to the full result dict; pass
    ``stream`` (a ``queue.Queue``) to additionally receive per-token
    dicts ``{"token", "logprob", "step"}`` as they land, terminated by
    ``None``.  Greedy outputs are identical to ``generate`` on the same
    weights: the prefill and per-step math run the same model code, and
    each row's logits never depend on its neighbours.
    """

    def __init__(
        self,
        model,
        variables,
        slots: int = 8,
        prompt_buckets: Sequence[int] = (128, 256, 512, 1024),
        max_new_cap: int = 128,
        pad_id: int = 0,
        quant_kernel: bool = False,
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.slots = int(slots)
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.max_new_cap = int(max_new_cap)
        self.pad_id = int(pad_id)
        self.quant_kernel = bool(quant_kernel)
        self.l_buf = self.prompt_buckets[-1] + self.max_new_cap
        self.vocab = int(getattr(model, "vocab_size"))
        self._jax, self._jnp = jax, jnp

        # weight prep mirrors generate(): entry-dequant everything the
        # kernel won't consume, fold the rest — ONCE, outside any step
        from mlcomp_tpu.ops.quant import (
            dequantize_nonkernel_params,
            dequantize_params,
            fold_kernel_leaves,
            has_quantized,
        )

        if has_quantized(variables):
            if self.quant_kernel:
                variables = fold_kernel_leaves(
                    dequantize_nonkernel_params(variables, jnp.bfloat16)
                )
            else:
                variables = dequantize_params(variables, jnp.bfloat16)
        self.variables = jax.tree.map(jnp.asarray, variables)

        from mlcomp_tpu.models.generation import init_cache

        self._cache = init_cache(model, self.slots, self.l_buf)
        self._last_logits = jnp.zeros((self.slots, self.vocab), jnp.float32)
        self._presence = jnp.zeros((self.slots, self.vocab), jnp.bool_)
        self._rng = jax.random.PRNGKey(seed)
        self._host: List[Optional[_Slot]] = [None] * self.slots
        self._broken: Optional[Exception] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._stats = {"requests": 0, "steps": 0, "prefills": 0}
        self.step_count = 0
        self._fns: Dict[Any, Any] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public

    def submit(
        self,
        prompt_ids: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        logprobs: bool = False,
        repetition_penalty: float = 1.0,
        stream: Optional["queue.Queue"] = None,
        _count: bool = True,
    ) -> Future:
        ids = [int(t) for t in prompt_ids]
        if not ids:
            raise ValueError("prompt must be non-empty")
        n_new = int(max_new_tokens)
        if n_new <= 0:
            raise ValueError("max_new_tokens must be positive")
        if n_new > self.max_new_cap:
            raise ValueError(
                f"max_new_tokens {n_new} exceeds the engine cap "
                f"{self.max_new_cap}"
            )
        self._bucket(len(ids))  # validate now, in the caller thread
        if self._stop.is_set():
            # a submit racing close() must fail HERE — after close's
            # queue drain nobody reads the queue, so an enqueued request
            # would hold an unresolvable Future
            raise RuntimeError("decode engine closed")
        if self._broken is not None:
            raise RuntimeError(
                f"decode engine is down: {self._broken!r}"
            ) from self._broken
        fut: Future = Future()
        self._queue.put({
            "ids": ids, "n_new": n_new, "future": fut,
            "temperature": float(temperature),
            "top_k": self.vocab if top_k is None else int(top_k),
            "top_p": 1.0 if top_p is None else float(top_p),
            "eos_id": -1 if eos_id is None else int(eos_id),
            "logprobs": bool(logprobs),
            "repetition_penalty": float(repetition_penalty),
            "stream": stream,
            "t_submit": time.perf_counter(),
        })
        if self._stop.is_set():
            # close() may have drained the queue between the check above
            # and our put; resolve the future ourselves (idempotent —
            # see _fail_future; a duplicate stream None is harmless, the
            # consumer stops at the first)
            if stream is not None:
                stream.put(None)
            _fail_future(fut, RuntimeError("decode engine closed"))
        if _count:
            # warmup's dummy submissions pass _count=False so the
            # service-visible request count means real requests only
            self._stats["requests"] += 1
        return fut

    def stats(self) -> Dict[str, Any]:
        active = sum(1 for s in self._host if s is not None)
        return {
            **self._stats,
            "queue_depth": self._queue.qsize(),
            "active_slots": active,
            "slots": self.slots,
        }

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
        # nobody may be left waiting on a future/stream that will never
        # resolve: fail in-flight rows and drain the queue
        err = RuntimeError("decode engine closed")
        for i in range(self.slots):
            self._finish(i, error=err)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req["stream"] is not None:
                req["stream"].put(None)
            _fail_future(req["future"], err)

    # ----------------------------------------------------------- programs

    def _bucket(self, n: int) -> int:
        # the window batcher's bucket policy, shared (serve.py)
        from mlcomp_tpu.serve import _bucket

        return _bucket(n, self.prompt_buckets, "prompt length")

    def _apply(self, *args, **kwargs):
        if self.quant_kernel:
            from mlcomp_tpu.ops.quant import quant_kernel_interception

            with quant_kernel_interception():
                return self.model.apply(*args, **kwargs)
        return self.model.apply(*args, **kwargs)

    def _prefill_fn(self, s_bucket: int):
        key = ("prefill", s_bucket)
        if key not in self._fns:
            jax, jnp = self._jax, self._jnp
            from mlcomp_tpu.models.generation import init_cache

            def prefill(variables, prompt, mask):
                cache = init_cache(self.model, 1, self.l_buf)
                positions = jnp.maximum(
                    jnp.cumsum(mask, axis=1) - 1, 0
                ).astype(jnp.int32)
                kv_mask = jnp.concatenate(
                    [mask, jnp.ones((1, self.l_buf - s_bucket), jnp.bool_)],
                    axis=1,
                )
                logits, upd = self._apply(
                    {**variables, "cache": cache}, prompt, decode=True,
                    positions=positions, kv_mask=kv_mask, mutable=["cache"],
                )
                return logits[:, -1].astype(jnp.float32), upd["cache"]

            self._fns[key] = jax.jit(prefill)
        return self._fns[key]

    def _insert_fn(self):
        if "insert" not in self._fns:
            jax = self._jax

            def insert(cache, last_logits, presence, row_cache, row_logits,
                       row_presence, slot):
                cache = jax.tree.map(
                    lambda ec, rc: ec if rc.ndim == 0
                    else ec.at[slot].set(rc[0]),
                    cache, row_cache,
                )
                return (
                    cache,
                    last_logits.at[slot].set(row_logits[0]),
                    presence.at[slot].set(row_presence[0]),
                )

            self._fns["insert"] = jax.jit(insert, donate_argnums=(0, 1, 2))
        return self._fns["insert"]

    def _step_fn(self):
        if "step" not in self._fns:
            jax, jnp = self._jax, self._jnp
            from mlcomp_tpu.models.generation import sample_token_rowwise

            def step(variables, cache, last_logits, presence, cursors,
                     kv_start, positions, active, rng, t_row, k_row, p_row,
                     rp_row):
                rows = jnp.arange(self.slots)
                raw = last_logits

                def penalized():
                    rp = rp_row[:, None]
                    return jnp.where(
                        presence, jnp.where(raw > 0, raw / rp, raw * rp), raw
                    )

                adj = jax.lax.cond(
                    jnp.any(rp_row != 1.0), penalized, lambda: raw
                )
                tok = sample_token_rowwise(rng, adj, t_row, k_row, p_row)
                tok = jnp.where(active, tok, jnp.int32(self.pad_id))
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(raw, axis=-1), tok[:, None], axis=-1
                )[:, 0]
                presence2 = presence.at[rows, tok].max(active)
                slots_iota = jnp.arange(self.l_buf, dtype=jnp.int32)
                kv_mask = slots_iota[None, :] >= kv_start[:, None]
                logits, upd = self._apply(
                    {**variables, "cache": cache}, tok[:, None], decode=True,
                    positions=positions[:, None], kv_mask=kv_mask,
                    cache_cursor=cursors, mutable=["cache"],
                )
                return (
                    upd["cache"], logits[:, -1].astype(jnp.float32),
                    presence2, tok, lp,
                )

            self._fns["step"] = jax.jit(step, donate_argnums=(1, 2, 3))
        return self._fns["step"]

    # ----------------------------------------------------------- the loop

    def _admit(self, req) -> None:
        from mlcomp_tpu.serve import left_pad_row

        jnp = self._jnp
        slot = self._host.index(None)
        ids = req["ids"]
        s_bucket = self._bucket(len(ids))
        row, rmask = left_pad_row(ids, s_bucket, self.pad_id)
        prompt, mask = row[None], rmask[None]
        row_logits, row_cache = self._prefill_fn(s_bucket)(
            self.variables, jnp.asarray(prompt), jnp.asarray(mask)
        )
        row_presence = np.zeros((1, self.vocab), bool)
        if req["repetition_penalty"] != 1.0:
            row_presence[0, np.asarray(ids)] = True
        self._cache, self._last_logits, self._presence = self._insert_fn()(
            self._cache, self._last_logits, self._presence,
            row_cache, row_logits, jnp.asarray(row_presence),
            jnp.int32(slot),
        )
        self._host[slot] = _Slot(
            req,
            cursor=s_bucket,
            position=len(ids),
            start=s_bucket - len(ids),
            remaining=req["n_new"],
        )
        self._stats["prefills"] += 1

    def _finish(self, slot_idx: int, error: Optional[Exception] = None):
        sl = self._host[slot_idx]
        self._host[slot_idx] = None
        if sl is None:
            return
        req = sl.req
        if req["stream"] is not None:
            req["stream"].put(None)
        if error is not None:
            _fail_future(req["future"], error)
            return
        result = {
            "ids": [t for t, _ in sl.emitted],
            "latency_ms": round(
                (time.perf_counter() - req["t_submit"]) * 1e3, 2
            ),
            "batched_with": self.slots,
        }
        if req["logprobs"]:
            result["logprobs"] = [round(lp, 5) for _, lp in sl.emitted]
        req["future"].set_result(result)

    def _run_step(self) -> None:
        jax, jnp = self._jax, self._jnp
        cursors = np.zeros(self.slots, np.int32)
        starts = np.zeros(self.slots, np.int32)
        positions = np.zeros(self.slots, np.int32)
        active = np.zeros(self.slots, bool)
        t = np.zeros(self.slots, np.float32)
        k = np.full(self.slots, self.vocab, np.int32)
        p = np.ones(self.slots, np.float32)
        rp = np.ones(self.slots, np.float32)
        for i, sl in enumerate(self._host):
            if sl is None:
                continue
            active[i] = True
            cursors[i] = sl.cursor
            starts[i] = sl.start
            positions[i] = sl.position
            t[i] = sl.req["temperature"]
            k[i] = sl.req["top_k"]
            p[i] = sl.req["top_p"]
            rp[i] = sl.req["repetition_penalty"]
        self._rng, sub = jax.random.split(self._rng)
        out = self._step_fn()(
            self.variables, self._cache, self._last_logits, self._presence,
            jnp.asarray(cursors), jnp.asarray(starts), jnp.asarray(positions),
            jnp.asarray(active), sub, jnp.asarray(t), jnp.asarray(k),
            jnp.asarray(p), jnp.asarray(rp),
        )
        self._cache, self._last_logits, self._presence = out[:3]
        toks = np.asarray(out[3])
        lps = np.asarray(out[4])
        self.step_count += 1
        self._stats["steps"] += 1
        for i, sl in enumerate(self._host):
            if sl is None:
                continue
            tok, lp = int(toks[i]), float(lps[i])
            sl.emitted.append((tok, lp))
            if sl.req["stream"] is not None:
                sl.req["stream"].put({
                    "token": tok, "logprob": round(lp, 5),
                    "step": self.step_count,
                })
            sl.cursor += 1
            sl.position += 1
            sl.remaining -= 1
            if sl.remaining <= 0 or tok == sl.req["eos_id"]:
                self._finish(i)

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._broken is not None:
                # donated buffers may be gone: fail queued requests fast
                try:
                    req = self._queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                if req["stream"] is not None:
                    req["stream"].put(None)
                _fail_future(
                    req["future"],
                    RuntimeError(f"decode engine is down: {self._broken!r}"),
                )
                continue
            try:
                # admit as many queued requests as there are free slots —
                # each joins at THIS step boundary
                while None in self._host:
                    block = all(s is None for s in self._host)
                    try:
                        req = self._queue.get(timeout=0.2 if block else 0)
                    except queue.Empty:
                        break
                    try:
                        self._admit(req)
                    except Exception as e:
                        if req["stream"] is not None:
                            req["stream"].put(None)
                        if not req["future"].done():
                            req["future"].set_exception(e)
                if any(s is not None for s in self._host):
                    self._run_step()
            except Exception as e:  # engine-level failure: fail active rows
                self._broken = e
                for i in range(self.slots):
                    self._finish(i, error=e)
